"""The dual-pods controller: binds server-requesting Pods to providers.

Re-design of the reference's core reconciler (`pkg/controller/dual-pods/`,
esp. inference-server.go:170-762) as an asyncio controller over the cluster
store. Invariants preserved from the reference:

  * **engine awake => Pod bound** — bind is committed before instance
    create/wake; unbind sleeps (or deletes an obsolete) instance first;
  * binding state lives in Pod annotations only (requester ann, instance-id,
    server-port, engine-config, routing metadata) — restart recovery is just
    re-reading them (`recover_instance_state`);
  * per-node serialization: one worker per node drains that node's queue, so
    two requesters for the same chips never race;
  * deletion relays: provider deleted exogenously -> requester deleted (with
    UID precondition); troubled provider -> deleted; stopped instance ->
    requester deleted so the ReplicaSet heals;
  * requester finalizer delays its deletion until the provider is asleep;
  * ISC routing labels are stamped only while bound AND serving, and removed
    before sleep (deferred routing — EPP must not route to a sleeping pod);
  * launcher selection priority: has the sleeping target instance > free
    capacity without port conflict > reclaim victims (port-conflict first,
    then LRU) > create a new launcher pre-bound.

TPU deltas: chip sets are topology-aware IDs (not flat GPU indices); the
accelerator-memory budget before wake uses HBM bytes from the requester SPI.
"""

from __future__ import annotations

import asyncio
import json
import secrets
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, List, Optional, Set, Tuple

from ..api import constants as C
from ..api.types import EngineServerConfig, InferenceServerConfig, LauncherConfig
from ..utils import tracing
from ..utils.hashing import canonical_json, instance_id_for, sha256_hex, template_hash
from . import metrics as M
from .clients import InstanceNotFound, Transports
from .directpath import (
    DIRECT_PROVIDER_COMPONENT,
    LAST_USED_ANNOTATION,
    NOMINAL_HASH_ANNOTATION,
    ProviderData,
    load_chip_map,
    nominal_provider_pod,
    render_server_patch,
)
from ..utils.syncbarrier import KnowsProcessedSync
from .store import AlreadyExists, Conflict, InMemoryStore, NotFound

logger = logging.getLogger(__name__)

FINALIZER = "dual-pods.llm-d.ai/finalizer"

ISC_NAME_ANNOTATION = "isc-name"  # on instances, for GC
INFERENCE_PORT_ANNOTATION = "inference-port"  # on instances, for port conflicts


def _meta(pod: Dict[str, Any]) -> Dict[str, Any]:
    return pod.setdefault("metadata", {})


def _ann(pod: Dict[str, Any]) -> Dict[str, str]:
    return _meta(pod).setdefault("annotations", {})


def _labels(pod: Dict[str, Any]) -> Dict[str, str]:
    return _meta(pod).setdefault("labels", {})


def _deleting(pod: Dict[str, Any]) -> bool:
    return _meta(pod).get("deletionTimestamp") is not None


def pod_in_trouble(pod: Dict[str, Any]) -> bool:
    """restarts > 0 and not Ready (pod-helper.go:44-53)."""
    st = pod.get("status") or {}
    restarts = sum(
        int(cs.get("restartCount", 0)) for cs in st.get("containerStatuses", [])
    )
    return restarts > 0 and not pod_is_ready(pod)


def pod_is_ready(pod: Dict[str, Any]) -> bool:
    for cond in (pod.get("status") or {}).get("conditions", []):
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


@dataclass
class ServerData:
    """In-memory (rebuildable) state for one requester (controller.go:452-515)."""

    requester_uid: str
    chip_ids: Optional[List[str]] = None
    instance_id: str = ""
    server_port: int = 0
    engine_config: Optional[Dict[str, Any]] = None
    sleeping: Optional[bool] = None
    readiness_relayed: Optional[bool] = None
    first_ready_relayed: bool = False
    instances_deleted: int = 0
    start_time: float = field(default_factory=time.monotonic)
    path: str = ""  # hot | warm | cold


@dataclass
class LauncherData:
    """Per-launcher inventory: instance id -> last-used timestamp (LRU)."""

    instances: Dict[str, float] = field(default_factory=dict)


@dataclass
class DualPodsConfig:
    namespace: str = ""
    sleeper_limit: int = 1
    #: HBM bytes allowed in use (by others) before waking on a chip set;
    #: 0 disables the check. Reference: sleeperLimit x 4096 MiB.
    accelerator_sleeping_memory_limit_bytes: int = 0
    retry_base_s: float = 0.05
    retry_max_s: float = 2.0
    #: Hook invoked after the controller creates a launcher Pod object —
    #: deployment glue (or the test harness) makes the pod actually run.
    launcher_runtime: Optional[Callable[[Dict[str, Any]], Awaitable[None]]] = None
    #: Same for direct (server-patch path) provider Pods.
    provider_runtime: Optional[Callable[[Dict[str, Any]], Awaitable[None]]] = None


class Retry(Exception):
    def __init__(self, why: str, after: float = 0.0) -> None:
        super().__init__(why)
        self.after = after


class DualPodsController:
    def __init__(
        self,
        store: InMemoryStore,
        transports: Transports,
        cfg: Optional[DualPodsConfig] = None,
    ) -> None:
        self.store = store
        self.transports = transports
        self.cfg = cfg or DualPodsConfig()
        self.server_data: Dict[str, ServerData] = {}  # requester uid ->
        self.launcher_data: Dict[str, LauncherData] = {}  # launcher pod name ->
        # provider pod name -> duality label sets currently at 1, so unbind
        # can zero exactly what bind raised (reference: duality<-0 on unbind,
        # inference-server.go:764-780).
        self._duality_up: Dict[str, List[Tuple[str, str, str]]] = {}
        self._queues: Dict[str, asyncio.Queue] = {}
        self._workers: Dict[str, asyncio.Task] = {}
        self._enqueued_at: Dict[Tuple[str, Tuple[str, str, str]], float] = {}
        self._count_keys: Tuple[Set[str], Set[str]] = (set(), set())
        self._unsub: Optional[Callable[[], None]] = None
        self._stopping = False
        #: one-shot operator warning: namespace-scoped controller +
        #: hostNetwork launchers = port-collision protection weaker than
        #: the code path suggests (see _assign_launcher_port)
        self._warned_hostnet_ns_scope = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._idle_event = asyncio.Event()
        self._inflight = 0
        #: initial-batch rendezvous (knows-processed-sync.go:27-103): fires
        #: once every object present at start() had one reconcile pass
        self.initial_sync = KnowsProcessedSync()

    # ------------------------------------------------------------------ setup

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._unsub = self.store.subscribe(self._on_store_event)
        # initial sync: enqueue every requester and bound provider
        for obj in self.store.all_objects():
            self._classify_and_enqueue(obj)
        self.initial_sync.arm()

    async def stop(self) -> None:
        self._stopping = True
        if self._unsub:
            self._unsub()
        for task in self._workers.values():
            task.cancel()
        for task in list(self._workers.values()):
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def quiesce(self, timeout: float = 30.0) -> None:
        """Wait until all queues are drained (test convenience)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._inflight == 0 and all(
                q.empty() for q in self._queues.values()
            ):
                await asyncio.sleep(0.05)
                if self._inflight == 0 and all(
                    q.empty() for q in self._queues.values()
                ):
                    return
            await asyncio.sleep(0.02)
        raise TimeoutError("controller did not quiesce")

    # ------------------------------------------------------- event classifying

    def _on_store_event(self, event: str, obj: Dict[str, Any]) -> None:
        if self._loop is None:
            return
        self._loop.call_soon_threadsafe(self._classify_and_enqueue, obj)

    def _refresh_counts(self, ns: str) -> None:
        """fma_requester_count / fma_isc_count: recomputed from the informer
        cache on relevant events (reference keeps these via handler-driven
        gauges; the cache scan is cheap at controller scale). Keys that
        vanish are zeroed so dashboards don't show ghost series."""
        req_counts: Dict[str, int] = {}
        for pod in self.store.list("Pod", ns):
            if _deleting(pod):
                continue
            isc = (pod["metadata"].get("annotations") or {}).get(
                C.INFERENCE_SERVER_CONFIG_ANNOTATION
            )
            if isc:
                req_counts[isc] = req_counts.get(isc, 0) + 1
        isc_counts: Dict[str, int] = {}
        for obj in self.store.list(InferenceServerConfig.KIND, ns):
            lc = (obj.get("spec") or {}).get("launcherConfigName") or ""
            if lc:
                isc_counts[lc] = isc_counts.get(lc, 0) + 1
        prev_req, prev_isc = self._count_keys
        for k in prev_req - set(req_counts):
            M.REQUESTER_COUNT.labels(isc_name=k).set(0)
        for k, v in req_counts.items():
            M.REQUESTER_COUNT.labels(isc_name=k).set(v)
        for k in prev_isc - set(isc_counts):
            M.ISC_COUNT.labels(launcher_config_name=k).set(0)
        for k, v in isc_counts.items():
            M.ISC_COUNT.labels(launcher_config_name=k).set(v)
        self._count_keys = (set(req_counts), set(isc_counts))

    def _classify_and_enqueue(self, obj: Dict[str, Any]) -> None:
        kind = obj.get("kind")
        m = obj.get("metadata") or {}
        ns, name = m.get("namespace", ""), m.get("name", "")
        ann = m.get("annotations") or {}
        lab = m.get("labels") or {}
        if kind == "Pod":
            if (
                C.INFERENCE_SERVER_CONFIG_ANNOTATION in ann
                or C.SERVER_PATCH_ANNOTATION in ann
            ):
                node = (obj.get("spec") or {}).get("nodeName", "")
                self._refresh_counts(ns)
                self._enqueue(node, ("requester", ns, name))
            elif lab.get(C.COMPONENT_LABEL) == C.LAUNCHER_COMPONENT:
                node = (obj.get("spec") or {}).get("nodeName", "")
                req = ann.get(C.REQUESTER_ANNOTATION, "")
                if req:
                    self._enqueue(node, ("requester", ns, req.split("/")[0]))
                else:
                    self._enqueue(node, ("launcher-sweep", ns, name))
            elif lab.get(C.COMPONENT_LABEL) == DIRECT_PROVIDER_COMPONENT:
                req = ann.get(C.REQUESTER_ANNOTATION, "")
                if req:
                    node = ((obj.get("spec") or {}).get("nodeSelector") or {}).get(
                        "kubernetes.io/hostname", ""
                    )
                    self._enqueue(node, ("requester", ns, req.split("/")[0]))
        elif kind == InferenceServerConfig.KIND:
            self._refresh_counts(ns)
            self._enqueue("", ("isc-changed", ns, name))

    def _enqueue(self, node: str, item: Tuple[str, str, str]) -> None:
        q = self._queues.get(node)
        if q is None:
            q = asyncio.Queue()
            self._queues[node] = q
            assert self._loop is not None
            self._workers[node] = self._loop.create_task(self._worker(node, q))
        M.INNER_QUEUE_ADDS.labels(node=node or "-").inc()
        self.initial_sync.note_pending(item)
        # queue-wait measurement (queue_duration_seconds, controller.go:206-242);
        # first-enqueue wins so a retry's wait measures from its re-add
        self._enqueued_at.setdefault((node, item), time.monotonic())
        q.put_nowait(item)
        M.INNER_QUEUE_DEPTH.labels(node=node or "-").set(q.qsize())

    async def _worker(self, node: str, q: asyncio.Queue) -> None:
        attempts: Dict[Tuple[str, str, str], int] = {}
        while not self._stopping:
            item = await q.get()
            self._inflight += 1
            M.INNER_QUEUE_DEPTH.labels(node=node or "-").set(q.qsize())
            t_enq = self._enqueued_at.pop((node, item), None)
            if t_enq is not None:
                M.QUEUE_DURATION.labels(node=node or "-").observe(
                    time.monotonic() - t_enq
                )
            t0 = time.monotonic()
            try:
                await self._process(item)
                attempts.pop(item, None)
            except Retry as r:
                n = attempts.get(item, 0) + 1
                attempts[item] = n
                delay = r.after or min(
                    self.cfg.retry_base_s * (2 ** min(n, 6)), self.cfg.retry_max_s
                )
                M.INNER_QUEUE_RETRIES.labels(node=node or "-").inc()
                logger.debug("retry %s in %.2fs: %s", item, delay, r)
                self._schedule_retry(node, item, delay)
            except Exception:
                n = attempts.get(item, 0) + 1
                attempts[item] = n
                delay = min(self.cfg.retry_base_s * (2 ** min(n, 6)), self.cfg.retry_max_s)
                M.INNER_QUEUE_RETRIES.labels(node=node or "-").inc()
                logger.exception("processing %s failed; retry in %.2fs", item, delay)
                self._schedule_retry(node, item, delay)
            finally:
                M.WORK_DURATION.labels(node=node or "-").observe(
                    time.monotonic() - t0
                )
                self.initial_sync.note_processed(item)
                self._inflight -= 1
                q.task_done()

    def _schedule_retry(self, node: str, item, delay: float) -> None:
        self._inflight += 1  # count scheduled retries as in-flight for quiesce

        def requeue() -> None:
            self._inflight -= 1
            if not self._stopping:
                self._enqueue(node, item)

        assert self._loop is not None
        self._loop.call_later(delay, requeue)

    async def _process(self, item: Tuple[str, str, str]) -> None:
        kind, ns, name = item
        if kind == "requester":
            await self._reconcile_requester(ns, name)
        elif kind == "launcher-sweep":
            await self._sweep_launcher(ns, name)
        elif kind == "isc-changed":
            await self._gc_obsolete_instances(ns, name)
            # re-reconcile requesters referencing this ISC
            for pod in self.store.list("Pod", ns):
                if (pod["metadata"].get("annotations") or {}).get(
                    C.INFERENCE_SERVER_CONFIG_ANNOTATION
                ) == name:
                    node = (pod.get("spec") or {}).get("nodeName", "")
                    self._enqueue(node, ("requester", ns, pod["metadata"]["name"]))

    # ----------------------------------------------------------- main machine

    def _providers_for(self, ns: str, req_name: str) -> List[Dict[str, Any]]:
        def is_bound_to(pod: Dict[str, Any]) -> bool:
            if (pod["metadata"].get("labels") or {}).get(C.COMPONENT_LABEL) not in (
                C.LAUNCHER_COMPONENT,
                DIRECT_PROVIDER_COMPONENT,
            ):
                return False
            v = (pod["metadata"].get("annotations") or {}).get(
                C.REQUESTER_ANNOTATION, ""
            )
            return v.split("/")[0] == req_name

        return self.store.list("Pod", ns, predicate=is_bound_to)

    @staticmethod
    def _is_direct(pod: Dict[str, Any]) -> bool:
        return (
            (pod["metadata"].get("labels") or {}).get(C.COMPONENT_LABEL)
            == DIRECT_PROVIDER_COMPONENT
        )

    async def _reconcile_requester(self, ns: str, name: str) -> None:
        req = self.store.try_get("Pod", ns, name)
        providers = self._providers_for(ns, name)

        if req is None:
            # requester gone entirely: unbind any provider still pointing at it
            for p in providers:
                await self._ensure_unbound(ns, p)
            return

        uid = req["metadata"]["uid"]
        # drop providers bound to a previous incarnation (same name, new uid)
        stale = [
            p
            for p in providers
            if "/" in (p["metadata"].get("annotations") or {}).get(C.REQUESTER_ANNOTATION, "")
            and p["metadata"]["annotations"][C.REQUESTER_ANNOTATION].split("/")[1] != uid
        ]
        for p in stale:
            await self._ensure_unbound(ns, p)
        providers = [p for p in providers if p not in stale]
        provider = providers[0] if providers else None

        if _deleting(req):
            if provider is not None:
                await self._ensure_unbound(ns, provider)
            await self._remove_finalizer("Pod", ns, name)
            self.server_data.pop(uid, None)
            return

        if provider is not None and _deleting(provider):
            # exogenous provider deletion: relay to the requester (with UID
            # precondition), then let the provider finish dying.
            try:
                await asyncio.to_thread(
                    self.store.delete, "Pod", ns, name, expect_uid=uid
                )
            except (NotFound, Conflict):
                pass
            await self._remove_finalizer("Pod", ns, provider["metadata"]["name"])
            for key in self._duality_up.pop(provider["metadata"]["name"], []):
                M.DUALITY.labels(isc_name=key[0], chip=key[1], node=key[2]).set(0)
            return

        if provider is not None and pod_in_trouble(provider):
            logger.warning("provider %s in trouble; deleting", provider["metadata"]["name"])
            await asyncio.to_thread(
                self.store.delete, "Pod", ns, provider["metadata"]["name"]
            )
            return

        # node must be schedulable/known
        node = (req.get("spec") or {}).get("nodeName", "")
        if not node:
            raise Retry("requester not scheduled yet", after=0.2)

        sd = self.server_data.get(uid)
        if sd is None:
            sd = ServerData(requester_uid=uid)
            self.server_data[uid] = sd

        # A requester with no provider yet on a cordoned node can never be
        # served — delete it so its ReplicaSet reschedules elsewhere
        # (inference-server.go:603-613).
        if provider is None:
            node_obj = self.store.try_get("Node", "", node)
            if node_obj is not None and (node_obj.get("spec") or {}).get(
                "unschedulable"
            ):
                logger.warning(
                    "deleting requester %s: node %s unschedulable and no "
                    "provider bound",
                    name,
                    node,
                )
                try:
                    # uid precondition: never delete a newer incarnation that
                    # raced in under the same name
                    await asyncio.to_thread(
                        self.store.delete, "Pod", ns, name, expect_uid=uid
                    )
                except (NotFound, Conflict):
                    pass
                await self._remove_finalizer("Pod", ns, name)
                self.server_data.pop(uid, None)
                return

        # chip discovery via the requester SPI (once)
        if sd.chip_ids is None:
            spi = self.transports.requester_spi(req)
            try:
                sd.chip_ids = await spi.accelerators()
            except Exception as e:
                raise Retry(f"chip discovery: {e}", after=0.2)

        ann = req["metadata"].get("annotations") or {}
        isc_name = ann.get(C.INFERENCE_SERVER_CONFIG_ANNOTATION, "")
        patch_tmpl = ann.get(C.SERVER_PATCH_ANNOTATION, "")
        if isc_name and patch_tmpl:
            await self._set_status(
                ns,
                name,
                ["server-patch and inference-server-config are mutually exclusive"],
            )
            return
        # A provider of the wrong kind (requester annotations were switched
        # between the two paths while bound) can't be driven by either state
        # machine — unbind it and start clean.
        if provider is not None and self._is_direct(provider) != bool(patch_tmpl):
            await self._ensure_unbound(ns, provider)
            provider = None
        if patch_tmpl:
            await self._reconcile_direct(ns, req, provider, patch_tmpl, node, sd)
            return
        if not isc_name:
            await self._set_status(ns, name, ["no inference-server-config annotation"])
            return
        isc_obj = self.store.try_get(InferenceServerConfig.KIND, ns, isc_name)
        if isc_obj is None:
            await self._set_status(ns, name, [f"InferenceServerConfig {isc_name} not found"])
            raise Retry(f"ISC {isc_name} missing", after=0.5)
        isc = InferenceServerConfig.from_dict(isc_obj)

        acc_errors = self._validate_accelerators(ns, node, isc, sd.chip_ids or [])
        if acc_errors:
            # Misplacement is a terminal condition for this requester (the
            # scheduler gave it the wrong chips); surface it and stop —
            # actuating a non-contiguous TP engine would put collectives on
            # a non-ICI path.
            await self._set_status(ns, name, acc_errors)
            return

        gang_env: Optional[Dict[str, str]] = None
        if isc.spec.engine_server_config.accelerator.hosts > 1:
            gang_env = await self._await_gang_assignment(ns, name, sd)

        engine_cfg, instance_id = self._desired_instance(
            isc, isc_name, sd.chip_ids, extra_env=gang_env
        )
        sd.instance_id = instance_id
        sd.server_port = isc.spec.engine_server_config.port
        sd.engine_config = engine_cfg

        if provider is None:
            provider = await self._select_or_create_launcher(
                ns, req, isc, isc_name, sd
            )
            if provider is None:
                raise Retry("no launcher available yet", after=0.3)

        await self._reconcile_bound(ns, req, provider, isc, isc_name, sd)

    def _validate_accelerators(
        self, ns: str, node: str, isc: InferenceServerConfig, chip_ids: List[str]
    ) -> List[str]:
        """ISC ``accelerator.{chips,topology}`` vs the requester-reported
        chip set — topology-aware placement validation (SURVEY §7; the
        reference's flat equivalent is the GPU count/index check,
        inference-server.go:384-399, which cannot express contiguity).

        Chip coordinates come from the chip-map ConfigMap when present,
        else from the ``...-<x>-<y>[-<z>]`` chip-ID convention the chip
        translators emit. Without coordinates only the count is checked.
        """
        from ..api.types import SliceTopology
        from ..parallel.topology import contiguous

        spec = isc.spec.engine_server_config.accelerator
        if not spec.specified:
            return []  # no declared requirements: scheduler placement stands
        errors: List[str] = []
        if spec.chips and len(chip_ids) != spec.chips:
            errors.append(
                f"accelerator.chips={spec.chips} but requester reports "
                f"{len(chip_ids)} chip(s)"
            )
        coords = self._chip_coords(ns, node, chip_ids)
        if coords is None:
            if spec.topology:
                errors.append(
                    f"accelerator.topology={spec.topology} required but chip "
                    "coordinates are unknown (no chip-map entry and "
                    "unparseable chip IDs)"
                )
            return errors
        if len(chip_ids) > 1 and not contiguous(coords):
            errors.append(
                f"chips {sorted(chip_ids)} are not ICI-contiguous "
                "(TP collectives would leave the mesh)"
            )
        # With hosts > 1, spec.topology is the GLOBAL slice shape; one
        # host's bounding box is only a tile of it, so the shape check is
        # the gang planner's job (parallel/multihost.plan_slice). Per-host
        # contiguity above still applies.
        if spec.topology and spec.hosts == 1 and not errors:
            want = SliceTopology.parse(spec.topology)
            spans = []
            ndim = len(coords[0]) if coords else 0
            for ax in range(ndim):
                vals = [c[ax] for c in coords]
                spans.append(max(vals) - min(vals) + 1)

            def norm(dims):
                d = sorted(int(x) for x in dims if int(x) > 1)
                return d or [1]

            if len(chip_ids) != want.num_chips or norm(spans) != norm(want.dims):
                got = "x".join(str(s) for s in spans) or "1"
                errors.append(
                    f"accelerator.topology={spec.topology} but placement is "
                    f"{got} ({len(chip_ids)} chip(s))"
                )
        return errors

    def _chip_coords(
        self, ns: str, node: str, chip_ids: List[str]
    ) -> Optional[List[Tuple[int, ...]]]:
        """ICI coordinates for `chip_ids`, or None when unknowable."""
        if not chip_ids:
            return []
        chip_map = load_chip_map(self.store, ns)
        if chip_map is not None:
            host = chip_map.host(node)
            if host is not None:
                by_id = host.by_id()
                if all(c in by_id for c in chip_ids):
                    return [by_id[c].coords for c in chip_ids]
        # fall back to the translator ID convention: ...-<x>-<y>[-<z>]
        coords: List[Tuple[int, ...]] = []
        for cid in chip_ids:
            parts = cid.split("-")
            tail: List[int] = []
            for p in reversed(parts):
                if p.isdigit() and len(tail) < 3:
                    tail.append(int(p))
                else:
                    break
            if len(tail) < 2:
                return None
            coords.append(tuple(reversed(tail)))
        if len({len(c) for c in coords}) != 1:
            return None
        return coords

    async def _await_gang_assignment(
        self, ns: str, req_name: str, sd: "ServerData"
    ) -> Dict[str, str]:
        """Multi-host ISC: publish this requester's chips so the slice-gang
        coordinator (controller/gang.py) can plan, then wait for the gang
        stamp. Its env makes the engine child join the jax.distributed job."""
        from .gang import gang_env_of

        chips = ",".join(sorted(sd.chip_ids or []))

        def publish(pod):
            ann = pod["metadata"].setdefault("annotations", {})
            if ann.get(C.ACCELERATORS_ANNOTATION) == chips:
                return None
            ann[C.ACCELERATORS_ANNOTATION] = chips
            return pod

        await self._amutate("Pod", ns, req_name, publish)
        pod = self.store.try_get("Pod", ns, req_name)
        env = gang_env_of(pod) if pod is not None else None
        if env is None:
            raise Retry("waiting for slice-gang assignment", after=0.5)
        return env

    def _desired_instance(
        self,
        isc: InferenceServerConfig,
        isc_name: str,
        chip_ids: List[str],
        extra_env: Optional[Dict[str, str]] = None,
    ) -> Tuple[Dict[str, Any], str]:
        """Desired instance config + deterministic ID
        (computeDesiredInstanceState, inference-server.go:1015-1057)."""
        esc = isc.spec.engine_server_config
        cfg = {
            "options": esc.options,
            "gpu_uuids": sorted(chip_ids),
            "env_vars": {**esc.env_vars, **(extra_env or {})},
            "annotations": {
                ISC_NAME_ANNOTATION: isc_name,
                INFERENCE_PORT_ANNOTATION: str(esc.port),
            },
        }
        iid = instance_id_for(esc, chip_ids, extra_env=extra_env)
        return cfg, iid

    # ------------------------------------------------------ launcher selection

    def _launcher_template(self, lc: LauncherConfig, node: str) -> Tuple[Dict[str, Any], str]:
        """Node-specialized launcher pod + its config hash. Shared with the
        populator (populator.build_launcher_template) so populator-created
        launchers hash identically and are eligible for selection here."""
        from .populator import build_launcher_template, specialize_to_node

        _, ti_hash = build_launcher_template(lc)
        pod = specialize_to_node(lc, node, ti_hash)
        return pod, pod["metadata"]["annotations"][C.LAUNCHER_CONFIG_HASH_ANNOTATION]

    async def _select_or_create_launcher(
        self,
        ns: str,
        req: Dict[str, Any],
        isc: InferenceServerConfig,
        isc_name: str,
        sd: ServerData,
    ) -> Optional[Dict[str, Any]]:
        lc_name = isc.spec.launcher_config_name
        if not lc_name:
            await self._set_status(ns, req["metadata"]["name"], ["ISC has no launcherConfigName"])
            return None
        lc_obj = self.store.try_get(LauncherConfig.KIND, ns, lc_name)
        if lc_obj is None:
            await self._set_status(ns, req["metadata"]["name"], [f"LauncherConfig {lc_name} not found"])
            raise Retry(f"LauncherConfig {lc_name} missing", after=0.5)
        lc = LauncherConfig.from_dict(lc_obj)
        node = req["spec"]["nodeName"]
        _, node_hash = self._launcher_template(lc, node)

        candidates = self.store.list(
            "Pod",
            ns,
            selector={
                C.COMPONENT_LABEL: C.LAUNCHER_COMPONENT,
                C.LAUNCHER_CONFIG_NAME_LABEL: lc_name,
            },
            predicate=lambda p: (
                (p.get("spec") or {}).get("nodeName") == node
                and not _deleting(p)
                and C.REQUESTER_ANNOTATION not in (p["metadata"].get("annotations") or {})
                and (p["metadata"].get("annotations") or {}).get(
                    C.LAUNCHER_CONFIG_HASH_ANNOTATION
                )
                == node_hash
            ),
        )

        # gather inventories (also repairs the LRU bookkeeping)
        inventories: Dict[str, List[Dict[str, Any]]] = {}
        for cand in candidates:
            cname = cand["metadata"]["name"]
            try:
                inv = await self.transports.launcher(cand).list_instances()
            except Exception as e:
                logger.warning("inventory of %s failed: %s", cname, e)
                continue
            inventories[cname] = inv.get("instances", [])
            ld = self.launcher_data.setdefault(cname, LauncherData())
            for st in inventories[cname]:
                ld.instances.setdefault(st["instance_id"], time.monotonic())
            for known in list(ld.instances):
                if known not in {s["instance_id"] for s in inventories[cname]}:
                    del ld.instances[known]

        # priority 1: a launcher already holding the (sleeping) target instance
        for cand in candidates:
            cname = cand["metadata"]["name"]
            if any(
                s["instance_id"] == sd.instance_id
                for s in inventories.get(cname, [])
            ):
                sd.path = sd.path or "warm"
                return await self._bind(ns, req, cand, isc_name, sd)

        port = str(sd.server_port)
        bound_ids = self._bound_instance_ids(ns)

        def port_conflicts(states: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
            return [
                s
                for s in states
                if (s.get("annotations") or {}).get(INFERENCE_PORT_ANNOTATION) == port
            ]

        # priority 2: free capacity, no port conflict
        for cand in candidates:
            cname = cand["metadata"]["name"]
            states = inventories.get(cname)
            if states is None:
                continue
            if len(states) < lc.spec.max_instances and not port_conflicts(states):
                sd.path = sd.path or "cold"
                return await self._bind(ns, req, cand, isc_name, sd)

        # priority 3: reclaim — fewest deletions first; victims must be unbound
        best: Optional[Tuple[int, Dict[str, Any], List[str]]] = None
        for cand in candidates:
            cname = cand["metadata"]["name"]
            states = inventories.get(cname)
            if states is None:
                continue
            # port-conflict victims first; a *live* (bound) conflicting
            # instance makes this launcher unusable
            victims: List[str] = []
            usable = True
            for s in port_conflicts(states):
                if s["instance_id"] in bound_ids:
                    usable = False
                    break
                victims.append(s["instance_id"])
            if not usable:
                continue
            remaining = len(states) - len(victims)
            if remaining >= lc.spec.max_instances:
                # LRU victims among unbound instances
                ld = self.launcher_data.setdefault(cname, LauncherData())
                unbound = [
                    s["instance_id"]
                    for s in states
                    if s["instance_id"] not in bound_ids
                    and s["instance_id"] not in victims
                ]
                unbound.sort(key=lambda i: ld.instances.get(i, 0))
                need = remaining - lc.spec.max_instances + 1
                if len(unbound) < need:
                    continue
                victims.extend(unbound[:need])
            if best is None or len(victims) < best[0]:
                best = (len(victims), cand, victims)
        if best is not None:
            _, cand, victims = best
            handle = self.transports.launcher(cand)
            for vid in victims:
                try:
                    await handle.delete_instance(vid)
                    sd.instances_deleted += 1
                except InstanceNotFound:
                    pass
            sd.path = sd.path or "cold"
            return await self._bind(ns, req, cand, isc_name, sd)

        # nothing reusable: create a launcher pod, pre-bound so the populator
        # can't reap it (inference-server.go:719-761)
        return await self._create_launcher_pod(ns, req, lc, isc_name, sd, node)

    def _bound_instance_ids(self, ns: str) -> Set[str]:
        out: Set[str] = set()
        for pod in self.store.list(
            "Pod", ns, selector={C.COMPONENT_LABEL: C.LAUNCHER_COMPONENT}
        ):
            ann = pod["metadata"].get("annotations") or {}
            if C.REQUESTER_ANNOTATION in ann and C.INSTANCE_ID_ANNOTATION in ann:
                out.add(ann[C.INSTANCE_ID_ANNOTATION])
        return out

    async def _create_launcher_pod(
        self,
        ns: str,
        req: Dict[str, Any],
        lc: LauncherConfig,
        isc_name: str,
        sd: ServerData,
        node: str,
    ) -> Optional[Dict[str, Any]]:
        pod, _ = self._launcher_template(lc, node)
        pod["metadata"]["namespace"] = ns
        self._assign_launcher_port(pod, node)
        self._stamp_binding(pod, req, isc_name, sd)
        t0 = time.monotonic()
        created = await self._create_unique(pod, f"{lc.metadata.name}-{node}")
        if self.cfg.launcher_runtime is not None:
            await self.cfg.launcher_runtime(created)
        M.LAUNCHER_CREATE_SECONDS.labels(lcfg_name=lc.metadata.name).observe(
            time.monotonic() - t0
        )
        sd.path = "cold"
        logger.info(
            "created launcher pod %s pre-bound to %s",
            pod["metadata"]["name"],
            req["metadata"]["name"],
        )
        return self.store.try_get("Pod", ns, pod["metadata"]["name"])

    def _assign_launcher_port(
        self, pod: Dict[str, Any], node: str
    ) -> None:
        """hostNetwork launchers on one node share the host's port space: a
        second (third, ...) launcher gets the first free port above the
        default, recorded where both sides look — the launcher-port
        annotation (read by the controller's transport) and the
        FMA_LAUNCHER_PORT env (the launcher binds it). Pod-network
        launchers keep the fixed default: per-pod IPs cannot collide.
        Reference analogue: same-node port collision creates a
        differently-ported launcher (test/e2e/test-cases.sh:320)."""
        spec = pod.get("spec") or {}
        if not spec.get("hostNetwork"):
            return
        if self.cfg.namespace and not self._warned_hostnet_ns_scope:
            # Surface the scope caveat below as an operator-visible warning
            # instead of a code comment: a namespace-scoped informer cannot
            # provide the node-wide collision protection this scan implies.
            self._warned_hostnet_ns_scope = True
            logger.warning(
                "hostNetwork launchers with a namespace-scoped controller "
                "(namespace %r): the launcher-port collision scan only "
                "sees this namespace's informer cache, so controller "
                "instances watching OTHER namespaces can assign colliding "
                "ports on shared nodes. Deploy the controller "
                "cluster-scoped, or give each namespace a disjoint "
                "launcher port range.",
                self.cfg.namespace,
            )
        used = set()
        # hostNetwork port space is node-wide, not namespace-wide: scan
        # every launcher pod the store knows about regardless of namespace
        # (namespace=None = cache-wide), so launchers from LauncherConfigs
        # in different namespaces on the same node can't collide. Scope
        # caveat: KubeStore's informer watches a single namespace, so when
        # the controller runs namespace-scoped this still only sees its own
        # namespace plus its own cross-namespace write-throughs; full
        # protection against launchers created by OTHER controller
        # instances needs a cluster-scoped watch (deploy the controller
        # cluster-scoped, or give each namespace a disjoint port range).
        for other in self.store.list(
            "Pod", None, selector={C.COMPONENT_LABEL: C.LAUNCHER_COMPONENT}
        ):
            if (other.get("spec") or {}).get("nodeName") != node:
                continue
            ann = other["metadata"].get("annotations") or {}
            try:
                used.add(
                    int(
                        ann.get(
                            C.LAUNCHER_PORT_ANNOTATION,
                            C.LAUNCHER_SERVICE_PORT,
                        )
                    )
                )
            except (TypeError, ValueError):
                continue
        port = C.LAUNCHER_SERVICE_PORT
        while port in used:
            port += 1
        if port == C.LAUNCHER_SERVICE_PORT:
            return
        _ann(pod)[C.LAUNCHER_PORT_ANNOTATION] = str(port)
        for c in spec.get("containers") or []:
            c.setdefault("env", []).append(
                {"name": "FMA_LAUNCHER_PORT", "value": str(port)}
            )

    def _stamp_binding(
        self, pod: Dict[str, Any], req: Dict[str, Any], isc_name: str, sd: ServerData
    ) -> None:
        """Binding = one metadata stamp (bind, inference-server.go:1430-1483):
        requester ann + finalizer + dual label + instance-state annotations."""
        rm = req["metadata"]
        ann = _ann(pod)
        ann[C.REQUESTER_ANNOTATION] = f"{rm['name']}/{rm['uid']}"
        ann[C.INSTANCE_ID_ANNOTATION] = sd.instance_id
        ann[C.SERVER_PORT_ANNOTATION] = str(sd.server_port)
        ann[C.ENGINE_CONFIG_ANNOTATION] = canonical_json(sd.engine_config)
        ann[C.LAUNCHER_BASED_ANNOTATION] = "true"
        ann[ISC_NAME_ANNOTATION] = isc_name
        _labels(pod)[C.DUAL_LABEL] = rm["name"]
        fins = _meta(pod).setdefault("finalizers", [])
        if FINALIZER not in fins:
            fins.append(FINALIZER)

    async def _bind(
        self,
        ns: str,
        req: Dict[str, Any],
        launcher_pod: Dict[str, Any],
        isc_name: str,
        sd: ServerData,
    ) -> Optional[Dict[str, Any]]:
        name = launcher_pod["metadata"]["name"]
        try:
            def apply(pod: Dict[str, Any]) -> Dict[str, Any]:
                if C.REQUESTER_ANNOTATION in (pod["metadata"].get("annotations") or {}):
                    raise Conflict(f"{name} got bound concurrently")
                self._stamp_binding(pod, req, isc_name, sd)
                return pod

            bound = await asyncio.to_thread(
                self.store.mutate, "Pod", ns, name, apply
            )
        except (Conflict, NotFound) as e:
            raise Retry(f"bind {name}: {e}", after=0.1)
        ld = self.launcher_data.setdefault(name, LauncherData())
        ld.instances[sd.instance_id] = time.monotonic()
        logger.info("bound %s -> %s", req["metadata"]["name"], name)
        return bound

    # --------------------------------------------------------- the bound path

    async def _reconcile_bound(
        self,
        ns: str,
        req: Dict[str, Any],
        provider: Dict[str, Any],
        isc: InferenceServerConfig,
        isc_name: str,
        sd: ServerData,
    ) -> None:
        """Traced entry: every HTTP call inside (launcher REST, engine
        admin, SPI relay — all through clients.py) becomes a child span
        and carries the traceparent downstream, so one reconcile pass of
        one actuation is one coherent trace (docs/tracing.md)."""
        with tracing.span(
            "controller.reconcile_bound",
            requester=req["metadata"]["name"],
            provider=provider["metadata"]["name"],
            isc=isc_name,
            path=sd.path or "",
        ):
            await self._reconcile_bound_impl(
                ns, req, provider, isc, isc_name, sd
            )

    async def _reconcile_bound_impl(
        self,
        ns: str,
        req: Dict[str, Any],
        provider: Dict[str, Any],
        isc: InferenceServerConfig,
        isc_name: str,
        sd: ServerData,
    ) -> None:
        pname = provider["metadata"]["name"]
        self.recover_instance_state(provider, sd)
        handle = self.transports.launcher(provider)

        # launcher inventory sync incl. stopped-instance handling
        try:
            inv = await handle.list_instances()
        except Exception as e:
            raise Retry(f"launcher {pname} unreachable: {e}", after=0.2)
        states = {s["instance_id"]: s for s in inv.get("instances", [])}
        await self._sweep_states(ns, pname, states)

        inst = states.get(sd.instance_id)
        if inst is not None and inst.get("status") == "stopped":
            # stopped instance recovery: delete the requester; the ReplicaSet
            # recreates it and reconciliation starts clean (test-cases.sh:833).
            logger.warning(
                "instance %s on %s stopped; deleting requester %s",
                sd.instance_id,
                pname,
                req["metadata"]["name"],
            )
            try:
                await handle.delete_instance(sd.instance_id)
            except InstanceNotFound:
                pass
            await asyncio.to_thread(
                self.store.delete,
                "Pod",
                ns,
                req["metadata"]["name"],
                expect_uid=req["metadata"]["uid"],
            )
            return
        if inst is None:
            try:
                await handle.create_named_instance(sd.instance_id, sd.engine_config)
                sd.path = sd.path or "cold"
                sd.sleeping = False
            except Exception as e:
                raise Retry(f"create instance: {e}", after=0.2)

        engine = self.transports.engine_admin(provider, sd.server_port)
        try:
            sleeping = await engine.is_sleeping()
        except Exception as e:
            raise Retry(f"is_sleeping: {e}", after=0.3)
        if sleeping:
            await self._check_memory_budget(req, sd)
            try:
                await engine.wake_up()
            except Exception as e:
                raise Retry(f"wake_up: {e}", after=0.3)
            sd.path = sd.path or "warm"
        sd.sleeping = False
        self.launcher_data.setdefault(pname, LauncherData()).instances[
            sd.instance_id
        ] = time.monotonic()

        # readiness relay + deferred routing labels
        healthy = await engine.healthy()
        if healthy:
            await self._apply_routing_metadata(ns, pname, isc)
            await self._apply_sleeping_label(ns, pname, "false")
            await self._ensure_req_state(ns, req, sd, pname)
            if sd.readiness_relayed is not True:
                spi = self.transports.requester_spi(req)
                try:
                    await spi.become_ready()
                except Exception as e:
                    raise Retry(f"become-ready: {e}", after=0.2)
                sd.readiness_relayed = True
                if not sd.first_ready_relayed:
                    sd.first_ready_relayed = True
                    path = sd.path or "hot"
                    M.ACTUATION_SECONDS.labels(
                        path=path,
                        instancesDeleted=str(sd.instances_deleted),
                        isc_name=isc_name,
                    ).observe(time.monotonic() - sd.start_time)
                    node = req["spec"].get("nodeName", "")
                    keys = [(isc_name, chip, node) for chip in sd.chip_ids or []]
                    for key in keys:
                        M.DUALITY.labels(
                            isc_name=key[0], chip=key[1], node=key[2]
                        ).set(1)
                    self._duality_up[pname] = keys
        else:
            await self._apply_sleeping_label(ns, pname, "false")
            await self._ensure_req_state(ns, req, sd, pname)
            if sd.readiness_relayed is True:
                spi = self.transports.requester_spi(req)
                try:
                    await spi.become_unready()
                except Exception:
                    pass
                sd.readiness_relayed = False
            raise Retry("engine not serving yet", after=0.3)

    async def _check_memory_budget(self, req: Dict[str, Any], sd: ServerData) -> None:
        limit = self.cfg.accelerator_sleeping_memory_limit_bytes
        if limit <= 0:
            return
        spi = self.transports.requester_spi(req)
        try:
            usage = await spi.accelerator_memory()
        except Exception:
            return
        used = sum(usage.get(c, 0) for c in sd.chip_ids or [])
        if used > limit:
            raise Retry(
                f"HBM in use ({used}B) above sleeping budget ({limit}B); "
                "waiting for sleepers to drain",
                after=1.0,
            )

    # ------------------------------------------------- direct path (M2 scope)

    async def _reconcile_direct(
        self,
        ns: str,
        req: Dict[str, Any],
        provider: Optional[Dict[str, Any]],
        patch_tmpl: str,
        node: str,
        sd: ServerData,
    ) -> None:
        """Server-patch path: derive the nominal provider from the requester,
        reuse a sleeping twin or create one (getNominalServerProvidingPod +
        the direct branch of infSvrItem.process, inference-server.go:617-668)."""
        name = req["metadata"]["name"]
        chip_map = load_chip_map(self.store, ns)
        try:
            patch = render_server_patch(patch_tmpl, ProviderData(node_name=node))
            nominal = nominal_provider_pod(req, patch, node, sd.chip_ids or [], chip_map)
        except ValueError as e:
            await self._set_status(ns, name, [f"server-patch: {e}"])
            return
        want_hash = nominal["metadata"]["annotations"][NOMINAL_HASH_ANNOTATION]
        if provider is not None:
            # The committed binding is authoritative while bound: drive the
            # engine at the port recorded at bind time, not at whatever the
            # (possibly edited) patch renders to now.
            committed = (provider["metadata"].get("annotations") or {}).get(
                C.SERVER_PORT_ANNOTATION
            )
            sd.server_port = int(
                committed
                or nominal["metadata"]["annotations"][C.SERVER_PORT_ANNOTATION]
            )
        else:
            sd.server_port = int(
                nominal["metadata"]["annotations"][C.SERVER_PORT_ANNOTATION]
            )

        if provider is None:
            twin = self._find_sleeping_twin(ns, node, want_hash)
            if twin is not None:
                sd.path = sd.path or "warm"
                provider = await self._bind_direct(ns, req, twin)
            else:
                await asyncio.to_thread(
                    self._enforce_sleeper_budget, ns, node, sd.chip_ids or []
                )
                provider = await self._create_direct_provider(ns, req, nominal, sd)
            if provider is None:
                raise Retry("direct provider not available yet", after=0.2)

        await self._reconcile_bound_direct(ns, req, provider, sd)

    def _find_sleeping_twin(
        self, ns: str, node: str, want_hash: str
    ) -> Optional[Dict[str, Any]]:
        """Unbound sleeping direct provider with the same nominal hash on the
        same node (the `nominal` index lookup, inference-server.go:1848-1860)."""
        def match(pod: Dict[str, Any]) -> bool:
            m = pod["metadata"]
            ann = m.get("annotations") or {}
            return (
                (m.get("labels") or {}).get(C.COMPONENT_LABEL)
                == DIRECT_PROVIDER_COMPONENT
                and not _deleting(pod)
                and C.REQUESTER_ANNOTATION not in ann
                and ann.get(NOMINAL_HASH_ANNOTATION) == want_hash
                and ((pod.get("spec") or {}).get("nodeSelector") or {}).get(
                    "kubernetes.io/hostname"
                )
                == node
            )

        twins = self.store.list("Pod", ns, predicate=match)
        return twins[0] if twins else None

    async def _bind_direct(
        self, ns: str, req: Dict[str, Any], twin: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        name = twin["metadata"]["name"]
        rm = req["metadata"]
        try:
            def apply(pod: Dict[str, Any]) -> Dict[str, Any]:
                if C.REQUESTER_ANNOTATION in (pod["metadata"].get("annotations") or {}):
                    raise Conflict(f"{name} got bound concurrently")
                _ann(pod)[C.REQUESTER_ANNOTATION] = f"{rm['name']}/{rm['uid']}"
                _labels(pod)[C.DUAL_LABEL] = rm["name"]
                fins = _meta(pod).setdefault("finalizers", [])
                if FINALIZER not in fins:
                    fins.append(FINALIZER)
                return pod

            bound = await asyncio.to_thread(
                self.store.mutate, "Pod", ns, name, apply
            )
        except (Conflict, NotFound) as e:
            raise Retry(f"bind twin {name}: {e}", after=0.1)
        logger.info("bound %s -> sleeping twin %s", rm["name"], name)
        return bound

    async def _create_direct_provider(
        self,
        ns: str,
        req: Dict[str, Any],
        nominal: Dict[str, Any],
        sd: ServerData,
    ) -> Optional[Dict[str, Any]]:
        rm = req["metadata"]
        pod = nominal
        pod["metadata"]["namespace"] = ns
        ann = _ann(pod)
        ann[C.REQUESTER_ANNOTATION] = f"{rm['name']}/{rm['uid']}"
        _labels(pod)[C.DUAL_LABEL] = rm["name"]
        fins = _meta(pod).setdefault("finalizers", [])
        if FINALIZER not in fins:
            fins.append(FINALIZER)
        created = await self._create_unique(pod, f"{rm['name']}-provider")
        if self.cfg.provider_runtime is not None:
            await self.cfg.provider_runtime(created)
        sd.path = "cold"
        logger.info("created direct provider %s for %s", pod["metadata"]["name"], rm["name"])
        return self.store.try_get("Pod", ns, pod["metadata"]["name"])

    def _enforce_sleeper_budget(
        self, ns: str, node: str, chip_ids: List[str]
    ) -> None:
        """At most `sleeper_limit` sleeping direct providers per chip: evict
        least-recently-used sleepers until the new provider fits
        (enforceSleeperBudget, inference-server.go:1353-1427)."""
        limit = self.cfg.sleeper_limit
        if limit <= 0:
            return

        def is_sleeper(pod: Dict[str, Any]) -> bool:
            m = pod["metadata"]
            return (
                (m.get("labels") or {}).get(C.COMPONENT_LABEL)
                == DIRECT_PROVIDER_COMPONENT
                and (m.get("labels") or {}).get(C.SLEEPING_LABEL) == "true"
                and C.REQUESTER_ANNOTATION not in (m.get("annotations") or {})
                and not _deleting(pod)
                and ((pod.get("spec") or {}).get("nodeSelector") or {}).get(
                    "kubernetes.io/hostname"
                )
                == node
            )

        sleepers = self.store.list("Pod", ns, predicate=is_sleeper)

        def chips_of(pod: Dict[str, Any]) -> Set[str]:
            raw = (pod["metadata"].get("annotations") or {}).get(
                C.ACCELERATORS_ANNOTATION, ""
            )
            return {c for c in raw.split(",") if c}

        def last_used(pod: Dict[str, Any]) -> float:
            try:
                return float(
                    (pod["metadata"].get("annotations") or {}).get(
                        LAST_USED_ANNOTATION, "0"
                    )
                )
            except ValueError:
                return 0.0

        for chip in chip_ids:
            on_chip = [p for p in sleepers if chip in chips_of(p)]
            on_chip.sort(key=last_used)
            while len(on_chip) > limit:
                victim = on_chip.pop(0)
                vname = victim["metadata"]["name"]
                try:
                    self.store.delete("Pod", ns, vname)
                    logger.info("sleeper budget: evicted %s (chip %s)", vname, chip)
                except NotFound:
                    pass
                sleepers = [p for p in sleepers if p["metadata"]["name"] != vname]

    async def _reconcile_bound_direct(
        self,
        ns: str,
        req: Dict[str, Any],
        provider: Dict[str, Any],
        sd: ServerData,
    ) -> None:
        with tracing.span(
            "controller.reconcile_bound",
            requester=req["metadata"]["name"],
            provider=provider["metadata"]["name"],
            isc="direct",
            path=sd.path or "",
        ):
            await self._reconcile_bound_direct_impl(ns, req, provider, sd)

    async def _reconcile_bound_direct_impl(
        self,
        ns: str,
        req: Dict[str, Any],
        provider: Dict[str, Any],
        sd: ServerData,
    ) -> None:
        pname = provider["metadata"]["name"]
        engine = self.transports.engine_admin(provider, sd.server_port)
        try:
            sleeping = await engine.is_sleeping()
        except Exception as e:
            raise Retry(f"is_sleeping({pname}): {e}", after=0.3)
        if sleeping:
            await self._check_memory_budget(req, sd)
            try:
                await engine.wake_up()
            except Exception as e:
                raise Retry(f"wake_up({pname}): {e}", after=0.3)
            sd.path = sd.path or "warm"
        sd.sleeping = False

        healthy = await engine.healthy()
        await self._apply_sleeping_label(ns, pname, "false")
        await self._ensure_req_state(ns, req, sd, pname)
        if not healthy:
            if sd.readiness_relayed is True:
                try:
                    await self.transports.requester_spi(req).become_unready()
                except Exception:
                    pass
                sd.readiness_relayed = False
            raise Retry("direct engine not serving yet", after=0.3)
        if sd.readiness_relayed is not True:
            try:
                await self.transports.requester_spi(req).become_ready()
            except Exception as e:
                raise Retry(f"become-ready: {e}", after=0.2)
            sd.readiness_relayed = True
            if not sd.first_ready_relayed:
                sd.first_ready_relayed = True
                M.ACTUATION_SECONDS.labels(
                    path=sd.path or "hot",
                    instancesDeleted=str(sd.instances_deleted),
                    isc_name="direct",
                ).observe(time.monotonic() - sd.start_time)
                node = req["spec"].get("nodeName", "")
                keys = [("direct", chip, node) for chip in sd.chip_ids or []]
                for key in keys:
                    M.DUALITY.labels(
                        isc_name=key[0], chip=key[1], node=key[2]
                    ).set(1)
                self._duality_up[pname] = keys

    async def _ensure_unbound_direct(self, ns: str, provider: Dict[str, Any]) -> None:
        """Sleep the engine and keep the Pod as a sleeping twin."""
        pname = provider["metadata"]["name"]
        ann = provider["metadata"].get("annotations") or {}
        if C.REQUESTER_ANNOTATION not in ann:
            return
        port = int(ann.get(C.SERVER_PORT_ANNOTATION, "0") or 0)
        engine = self.transports.engine_admin(provider, port)
        try:
            await engine.sleep(1)
        except Exception as e:
            logger.warning("sleep of direct provider %s failed: %s", pname, e)

        def apply(pod: Dict[str, Any]) -> Dict[str, Any]:
            a = _ann(pod)
            a.pop(C.REQUESTER_ANNOTATION, None)
            a[LAST_USED_ANNOTATION] = str(time.time())
            lab = _labels(pod)
            lab.pop(C.DUAL_LABEL, None)
            lab[C.SLEEPING_LABEL] = "true"
            fins = pod["metadata"].get("finalizers") or []
            if FINALIZER in fins:
                fins.remove(FINALIZER)
            return pod

        try:
            await asyncio.to_thread(self.store.mutate, "Pod", ns, pname, apply)
        except NotFound:
            pass
        for key in self._duality_up.pop(pname, []):
            M.DUALITY.labels(isc_name=key[0], chip=key[1], node=key[2]).set(0)
        logger.info("unbound direct provider %s (now a sleeping twin)", pname)

    # ---------------------------------------------------------------- unbind

    async def _ensure_unbound(self, ns: str, provider: Dict[str, Any]) -> None:
        """Sleep (or GC) the instance, then clear binding metadata in one
        update (ensureUnbound, inference-server.go:1669-1764)."""
        if self._is_direct(provider):
            await self._ensure_unbound_direct(ns, provider)
            return
        pname = provider["metadata"]["name"]
        ann = provider["metadata"].get("annotations") or {}
        if C.REQUESTER_ANNOTATION not in ann:
            return
        instance_id = ann.get(C.INSTANCE_ID_ANNOTATION, "")
        port = int(ann.get(C.SERVER_PORT_ANNOTATION, "0") or 0)
        isc_name = ann.get(ISC_NAME_ANNOTATION, "")

        # de-route before sleeping (EPP must stop routing first)
        await self._remove_routing_metadata(ns, pname)

        if instance_id:
            obsolete = self._instance_obsolete(ns, isc_name, instance_id, ann)
            handle = self.transports.launcher(provider)
            if obsolete:
                try:
                    await handle.delete_instance(instance_id)
                    logger.info("deleted obsolete instance %s on %s", instance_id, pname)
                except InstanceNotFound:
                    pass
                except Exception as e:
                    # Don't block the unbind: the instance stays on the
                    # launcher's inventory and _gc_obsolete_instances collects
                    # it on the next ISC event.
                    logger.warning(
                        "deleting obsolete instance %s on %s failed: %s",
                        instance_id,
                        pname,
                        e,
                    )
            else:
                engine = self.transports.engine_admin(provider, port)
                try:
                    await engine.sleep(1)
                except Exception as e:
                    logger.warning("sleep of %s failed: %s", instance_id, e)

        def apply(pod: Dict[str, Any]) -> Dict[str, Any]:
            a = _ann(pod)
            for key in (
                C.REQUESTER_ANNOTATION,
                C.INSTANCE_ID_ANNOTATION,
                C.SERVER_PORT_ANNOTATION,
                C.ENGINE_CONFIG_ANNOTATION,
                C.ISC_ROUTING_METADATA_ANNOTATION,
                ISC_NAME_ANNOTATION,
            ):
                a.pop(key, None)
            lab = _labels(pod)
            lab.pop(C.DUAL_LABEL, None)
            lab[C.SLEEPING_LABEL] = "true"
            fins = pod["metadata"].get("finalizers") or []
            if FINALIZER in fins:
                fins.remove(FINALIZER)
            return pod

        try:
            await asyncio.to_thread(self.store.mutate, "Pod", ns, pname, apply)
        except NotFound:
            pass
        for key in self._duality_up.pop(pname, []):
            M.DUALITY.labels(isc_name=key[0], chip=key[1], node=key[2]).set(0)
        logger.info("unbound provider %s", pname)

    def _instance_obsolete(
        self, ns: str, isc_name: str, instance_id: str, ann: Dict[str, str]
    ) -> bool:
        """Does the committed instance still match its ISC's current spec?
        (maybeDeleteObsoleteInstance, inference-server.go:1776-1835)."""
        if not isc_name:
            return False
        isc_obj = self.store.try_get(InferenceServerConfig.KIND, ns, isc_name)
        if isc_obj is None:
            return True
        isc = InferenceServerConfig.from_dict(isc_obj)
        try:
            cfg = json.loads(ann.get(C.ENGINE_CONFIG_ANNOTATION, "{}"))
            chips = cfg.get("gpu_uuids", [])
        except json.JSONDecodeError:
            return True
        from .gang import gang_env_from_instance_env

        return (
            instance_id_for(
                isc.spec.engine_server_config,
                chips,
                extra_env=gang_env_from_instance_env(cfg.get("env_vars")),
            )
            != instance_id
        )

    # --------------------------------------------------------------- sweeping

    async def _sweep_launcher(self, ns: str, name: str) -> None:
        """Unbound launcher changed (e.g. notifier signature): GC stopped
        instances (syncLauncherInstances, inference-server.go:2094-2182)."""
        pod = self.store.try_get("Pod", ns, name)
        if pod is None or _deleting(pod):
            self.launcher_data.pop(name, None)
            return
        try:
            inv = await self.transports.launcher(pod).list_instances()
        except Exception:
            return
        states = {s["instance_id"]: s for s in inv.get("instances", [])}
        await self._sweep_states(ns, name, states)

    async def _sweep_states(
        self, ns: str, launcher_name: str, states: Dict[str, Dict[str, Any]]
    ) -> None:
        bound = self._bound_instance_ids(ns)
        pod = self.store.try_get("Pod", ns, launcher_name)
        if pod is None:
            return
        handle = self.transports.launcher(pod)
        for iid, st in states.items():
            if st.get("status") == "stopped" and iid not in bound:
                try:
                    await handle.delete_instance(iid)
                    logger.info("GC'd stopped instance %s on %s", iid, launcher_name)
                except InstanceNotFound:
                    pass
        ld = self.launcher_data.setdefault(launcher_name, LauncherData())
        for iid in states:
            ld.instances.setdefault(iid, time.monotonic())
        for known in list(ld.instances):
            if known not in states:
                del ld.instances[known]

    async def _gc_obsolete_instances(self, ns: str, isc_name: str) -> None:
        """ISC changed: delete sleeping instances whose hash no longer matches
        (instanceGCItem, inference-server.go:1586-1663)."""
        isc_obj = self.store.try_get(InferenceServerConfig.KIND, ns, isc_name)
        bound = self._bound_instance_ids(ns)
        for pod in self.store.list(
            "Pod", ns, selector={C.COMPONENT_LABEL: C.LAUNCHER_COMPONENT}
        ):
            if _deleting(pod):
                continue
            try:
                inv = await self.transports.launcher(pod).list_instances()
            except Exception:
                continue
            for st in inv.get("instances", []):
                if (st.get("annotations") or {}).get(ISC_NAME_ANNOTATION) != isc_name:
                    continue
                iid = st["instance_id"]
                if iid in bound:
                    continue
                obsolete = True
                if isc_obj is not None:
                    from .gang import gang_env_from_instance_env

                    isc = InferenceServerConfig.from_dict(isc_obj)
                    chips = st.get("gpu_uuids") or []
                    obsolete = (
                        instance_id_for(
                            isc.spec.engine_server_config,
                            chips,
                            extra_env=gang_env_from_instance_env(
                                st.get("env_vars")
                            ),
                        )
                        != iid
                    )
                if obsolete:
                    try:
                        await self.transports.launcher(pod).delete_instance(iid)
                        logger.info(
                            "GC'd obsolete instance %s on %s (ISC %s changed)",
                            iid,
                            pod["metadata"]["name"],
                            isc_name,
                        )
                    except InstanceNotFound:
                        pass

    # ------------------------------------------------------- metadata helpers

    def recover_instance_state(self, provider: Dict[str, Any], sd: ServerData) -> None:
        """Rebuild ServerData from the annotations committed at bind time
        (inference-server.go:1235-1277). The committed binding is
        authoritative while bound — if the ISC changed since bind, the OLD
        instance keeps serving until unbind (where the obsolete check deletes
        instead of sleeping it); the new hash applies at the next bind."""
        ann = provider["metadata"].get("annotations") or {}
        if C.INSTANCE_ID_ANNOTATION in ann:
            sd.instance_id = ann[C.INSTANCE_ID_ANNOTATION]
        if C.SERVER_PORT_ANNOTATION in ann:
            sd.server_port = int(ann[C.SERVER_PORT_ANNOTATION])
        if C.ENGINE_CONFIG_ANNOTATION in ann:
            try:
                sd.engine_config = json.loads(ann[C.ENGINE_CONFIG_ANNOTATION])
            except json.JSONDecodeError:
                pass

    async def _amutate(self, kind: str, ns: str, name: str, fn) -> None:
        """`store.mutate` off the event loop (writes are blocking HTTP),
        swallowing NotFound (the object died; nothing to stamp)."""
        try:
            await asyncio.to_thread(self.store.mutate, kind, ns, name, fn)
        except NotFound:
            pass

    async def _create_unique(
        self, pod: Dict[str, Any], prefix: str
    ) -> Dict[str, Any]:
        """`metadata.generateName` semantics without server support in every
        test store: random suffix + retry on AlreadyExists (replaces the old
        time-derived suffix that wrapped every 100 s)."""
        for _ in range(8):
            pod["metadata"]["name"] = f"{prefix}-{secrets.token_hex(3)}"
            try:
                return await asyncio.to_thread(self.store.create, pod)
            except AlreadyExists:
                continue
        raise Retry(f"no free pod name under prefix {prefix}", after=0.2)

    async def _apply_routing_metadata(
        self, ns: str, provider_name: str, isc: InferenceServerConfig
    ) -> None:
        esc = isc.spec.engine_server_config
        if not esc.labels and not esc.annotations:
            return

        def apply(pod: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            routing = {"labels": esc.labels, "annotations": esc.annotations}
            a = _ann(pod)
            if a.get(C.ISC_ROUTING_METADATA_ANNOTATION) == canonical_json(routing):
                return None
            # drop keys from the previously-stamped routing set that are no
            # longer in the ISC (else stale labels keep routing traffic here)
            old_raw = a.get(C.ISC_ROUTING_METADATA_ANNOTATION)
            if old_raw:
                try:
                    old = json.loads(old_raw)
                except json.JSONDecodeError:
                    old = {}
                for k in old.get("labels", {}):
                    if k not in esc.labels:
                        _labels(pod).pop(k, None)
                for k in old.get("annotations", {}):
                    if k not in esc.annotations:
                        a.pop(k, None)
            _labels(pod).update(esc.labels)
            a.update(esc.annotations)
            a[C.ISC_ROUTING_METADATA_ANNOTATION] = canonical_json(routing)
            return pod

        await self._amutate("Pod", ns, provider_name, apply)

    async def _remove_routing_metadata(self, ns: str, provider_name: str) -> None:
        def apply(pod: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            a = _ann(pod)
            raw = a.get(C.ISC_ROUTING_METADATA_ANNOTATION)
            if raw is None:
                return None
            try:
                routing = json.loads(raw)
            except json.JSONDecodeError:
                routing = {"labels": {}, "annotations": {}}
            for k in routing.get("labels", {}):
                _labels(pod).pop(k, None)
            for k in routing.get("annotations", {}):
                a.pop(k, None)
            a.pop(C.ISC_ROUTING_METADATA_ANNOTATION, None)
            return pod

        await self._amutate("Pod", ns, provider_name, apply)

    async def _apply_sleeping_label(self, ns: str, pod_name: str, value: str) -> None:
        def apply(pod: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            if _labels(pod).get(C.SLEEPING_LABEL) == value:
                return None
            _labels(pod)[C.SLEEPING_LABEL] = value
            return pod

        await self._amutate("Pod", ns, pod_name, apply)

    async def _ensure_req_state(
        self, ns: str, req: Dict[str, Any], sd: ServerData, provider_name: str
    ) -> None:
        """Status ann, accelerators ann, dual/instance labels, finalizer — one
        conditional update (ensureReqState, inference-server.go:2028-2075)."""
        name = req["metadata"]["name"]

        def apply(pod: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            changed = False
            a = _ann(pod)
            lab = _labels(pod)
            want = {
                C.ACCELERATORS_ANNOTATION: ",".join(sorted(sd.chip_ids or [])),
                C.STATUS_ANNOTATION: canonical_json({"Errors": []}),
            }
            for k, v in want.items():
                if a.get(k) != v:
                    a[k] = v
                    changed = True
            want_labels = {C.DUAL_LABEL: provider_name, C.INSTANCE_LABEL: sd.instance_id}
            for k, v in want_labels.items():
                if lab.get(k) != v:
                    lab[k] = v
                    changed = True
            fins = pod["metadata"].setdefault("finalizers", [])
            if FINALIZER not in fins:
                fins.append(FINALIZER)
                changed = True
            return pod if changed else None

        await self._amutate("Pod", ns, name, apply)

    async def _set_status(self, ns: str, req_name: str, errors: List[str]) -> None:
        def apply(pod: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            a = _ann(pod)
            want = canonical_json({"Errors": errors})
            if a.get(C.STATUS_ANNOTATION) == want:
                return None
            a[C.STATUS_ANNOTATION] = want
            return pod

        await self._amutate("Pod", ns, req_name, apply)

    async def _remove_finalizer(self, kind: str, ns: str, name: str) -> None:
        def apply(obj: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            fins = obj["metadata"].get("finalizers") or []
            if FINALIZER not in fins:
                return None
            fins.remove(FINALIZER)
            obj["metadata"]["finalizers"] = fins
            return obj

        await self._amutate(kind, ns, name, apply)
