"""The control plane: dual-pods controller + launcher populator.

The reference's controllers (`pkg/controller/dual-pods`, 3.7k LoC Go;
`pkg/controller/launcher-populator`, 3.0k LoC Go) re-designed as asyncio
reconcilers over a pluggable *cluster store*:

  * :class:`~.store.InMemoryStore` — a kube-API-shaped ACID store with
    resourceVersions, finalizers, deletion timestamps, label selection, and
    watch streams. It is the test substrate (the reference needs a kind
    cluster for the same coverage) and defines the exact interface a real
    kube-API-backed store implements in deployment.
  * binding state is externalized to object annotations exactly as the
    reference does (controller restart recovery = re-reading annotations).
"""

from .store import Conflict, InMemoryStore, NotFound  # noqa: F401
from .dualpods import DualPodsController, DualPodsConfig  # noqa: F401
