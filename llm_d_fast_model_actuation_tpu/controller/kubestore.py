"""Kube-backed ClusterStore: the InMemoryStore interface against a real
kube-apiserver.

The controllers are store-agnostic (store.py's contract). This backend
gives them the production deployment path the reference gets from
client-go + generated informers (SURVEY.md §2.1 L6):

  * **informer cache**: one list+watch loop per resource kind keeps a local
    cache; all reads (`get`/`list`) are synchronous against it, like
    informer Listers;
  * **read-your-writes**: every successful write applies the server's
    response object to the cache immediately (keyed newest-by-RV), so a
    reconcile step sees its own writes without waiting for the watch echo;
  * **writes** go straight to the apiserver with kube's optimistic
    concurrency (409 -> Conflict, 404 -> NotFound, 422 -> AlreadyExists
    mapping); `mutate` is get-fresh + apply + PUT with conflict retry;
  * **watch recovery**: a broken/expired watch re-lists and re-watches
    (resync), then resumes from the new list RV.

Writes use blocking HTTP (urllib) — one short apiserver round trip inside a
reconcile step, the same cost profile as the reference's direct kube writes
from worker goroutines.
"""

from __future__ import annotations

import asyncio
import copy
import json
import logging
import ssl
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from .store import (
    ADDED,
    AlreadyExists,
    Conflict,
    DELETED,
    MODIFIED,
    NotFound,
    labels_match,
)

logger = logging.getLogger(__name__)

#: kind -> (api prefix, plural, namespaced)
KIND_PATHS: Dict[str, Tuple[str, str, bool]] = {
    "Pod": ("/api/v1", "pods", True),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "Node": ("/api/v1", "nodes", False),
    "InferenceServerConfig": (
        "/apis/fma.llm-d.ai/v1alpha1",
        "inferenceserverconfigs",
        True,
    ),
    "LauncherConfig": ("/apis/fma.llm-d.ai/v1alpha1", "launcherconfigs", True),
    "LauncherPopulationPolicy": (
        "/apis/fma.llm-d.ai/v1alpha1",
        "launcherpopulationpolicies",
        True,
    ),
}


def _rv_int(obj: Dict[str, Any]) -> int:
    try:
        return int((obj.get("metadata") or {}).get("resourceVersion", "0"))
    except (TypeError, ValueError):
        return 0


class KubeStore:
    def __init__(
        self,
        base_url: str,
        namespace: str,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        kinds: Optional[List[str]] = None,
        request_timeout_s: float = 15.0,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.namespace = namespace
        self._token = token
        #: bound service-account tokens rotate on disk (~1h TTL): re-read per
        #: request like client-go, never cache for the process lifetime
        self._token_file = token_file
        self._timeout = request_timeout_s
        self._ssl: Optional[ssl.SSLContext] = None
        if ca_file:
            self._ssl = ssl.create_default_context(cafile=ca_file)
        self.kinds = kinds or list(KIND_PATHS)
        self._cache: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._watchers: List[Callable[[str, Dict[str, Any]], None]] = []
        self._tasks: List[asyncio.Task] = []
        self._stopping = False

    @classmethod
    def in_cluster(cls, namespace: Optional[str] = None, **kw) -> "KubeStore":
        """Standard in-cluster wiring (Downward API service account)."""
        import os

        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        if namespace is None:
            with open(f"{sa}/namespace") as f:
                namespace = f.read().strip()
        return cls(
            f"https://{host}:{port}",
            namespace,
            token_file=f"{sa}/token",
            ca_file=f"{sa}/ca.crt",
            **kw,
        )

    def _bearer(self) -> Optional[str]:
        if self._token_file:
            try:
                with open(self._token_file) as f:
                    return f.read().strip()
            except OSError:
                return self._token
        return self._token

    # -- paths ---------------------------------------------------------------

    def _collection_path(self, kind: str, namespace: Optional[str] = None) -> str:
        prefix, plural, namespaced = KIND_PATHS[kind]
        if namespaced:
            return f"{prefix}/namespaces/{namespace or self.namespace}/{plural}"
        return f"{prefix}/{plural}"

    def _object_path(self, kind: str, name: str, namespace: Optional[str] = None) -> str:
        return f"{self._collection_path(kind, namespace)}/{name}"

    # -- raw HTTP (blocking; used for writes and relists) ----------------------

    def _request(
        self, method: str, path: str, body: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        bearer = self._bearer()
        req = urllib.request.Request(
            self.base_url + path,
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={
                "Content-Type": "application/json",
                "Accept": "application/json",
                **({"Authorization": f"Bearer {bearer}"} if bearer else {}),
            },
        )
        # Per-call latency logging discipline: every kube write logs its
        # start time, latency, and the new resourceVersion — the reference
        # does this on every write path (e.g. inference-server.go:1448-1459)
        # and its benchmark log-parsing relies on it.
        start = time.monotonic()
        try:
            with urllib.request.urlopen(
                req, timeout=self._timeout, context=self._ssl
            ) as resp:
                out = json.loads(resp.read() or b"{}")
                if method != "GET" and logger.isEnabledFor(logging.DEBUG):
                    logger.debug(
                        "k8s %s %s latencySecs=%.4f rv=%s",
                        method,
                        path,
                        time.monotonic() - start,
                        (out.get("metadata") or {}).get("resourceVersion", ""),
                    )
                return out
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            if e.code == 404:
                raise NotFound(f"{method} {path}: {detail}") from e
            if e.code == 409:
                if "AlreadyExists" in detail or method == "POST":
                    raise AlreadyExists(f"{path}: {detail}") from e
                raise Conflict(f"{path}: {detail}") from e
            raise RuntimeError(f"{method} {path} -> {e.code}: {detail}") from e

    # -- cache + events --------------------------------------------------------

    def _apply(self, event: str, obj: Dict[str, Any]) -> bool:
        """Apply an event to the cache; returns False if it's stale."""
        m = obj.get("metadata") or {}
        key = (obj.get("kind", ""), m.get("namespace", ""), m.get("name", ""))
        with self._lock:
            cur = self._cache.get(key)
            if event == DELETED:
                if cur is not None and _rv_int(cur) > _rv_int(obj):
                    return False
                self._cache.pop(key, None)
                return True
            if cur is not None and _rv_int(cur) >= _rv_int(obj):
                return False
            self._cache[key] = copy.deepcopy(obj)
            return True

    def _emit(self, event: str, obj: Dict[str, Any]) -> None:
        snapshot = copy.deepcopy(obj)
        for w in list(self._watchers):
            w(event, snapshot)

    def subscribe(self, handler: Callable[[str, Dict[str, Any]], None]) -> Callable[[], None]:
        self._watchers.append(handler)
        return lambda: self._watchers.remove(handler)

    # -- list+watch loops ------------------------------------------------------

    async def start(self) -> None:
        import aiohttp

        # no baked-in auth header: tokens rotate, so each watch request
        # attaches a freshly read bearer
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_read=None),
        )
        for kind in self.kinds:
            rv = await asyncio.get_running_loop().run_in_executor(
                None, self._relist, kind
            )
            self._tasks.append(
                asyncio.get_running_loop().create_task(self._watch_loop(kind, rv))
            )

    async def stop(self) -> None:
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        await self._session.close()

    def _relist(self, kind: str) -> str:
        body = self._request("GET", self._collection_path(kind))
        list_rv = (body.get("metadata") or {}).get("resourceVersion", "")
        try:
            list_rv_int = int(list_rv)
        except (TypeError, ValueError):
            list_rv_int = 0
        seen = set()
        for item in body.get("items", []):
            item.setdefault("kind", kind)
            m = item.get("metadata") or {}
            seen.add((kind, m.get("namespace", ""), m.get("name", "")))
            if self._apply(MODIFIED, item):
                self._emit(MODIFIED, item)
        # purge entries deleted while we weren't watching — but never ones
        # written AFTER the list was generated (their RV exceeds the list
        # RV; a concurrent create() on the loop thread must stay visible),
        # and only within the namespace the list actually covered
        # (cross-namespace writes are cached too but not listed here)
        _, _, namespaced = KIND_PATHS[kind]
        with self._lock:
            gone = [
                k
                for k, obj in self._cache.items()
                if k[0] == kind
                and (not namespaced or k[1] == self.namespace)
                and k not in seen
                and (not list_rv_int or _rv_int(obj) <= list_rv_int)
            ]
            removed = [self._cache.pop(k) for k in gone]
        for obj in removed:
            self._emit(DELETED, obj)
        return list_rv

    @staticmethod
    async def _iter_json_lines(stream):
        """Newline-delimited JSON from an aiohttp stream without the 64KB
        readline limit — real Pod watch events routinely exceed it
        (managedFields, env, volumes)."""
        buf = bytearray()
        async for chunk in stream.iter_any():
            buf.extend(chunk)
            while True:
                nl = buf.find(b"\n")
                if nl < 0:
                    break
                line = bytes(buf[:nl])
                del buf[: nl + 1]
                if line.strip():
                    yield json.loads(line)
        if buf.strip():
            yield json.loads(bytes(buf))

    async def _watch_loop(self, kind: str, rv: str) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopping:
            url = self.base_url + self._collection_path(kind)
            params = {"watch": "1"}
            if rv:
                params["resourceVersion"] = rv
            bearer = self._bearer()
            headers = {"Authorization": f"Bearer {bearer}"} if bearer else {}
            try:
                async with self._session.get(
                    url, params=params, headers=headers, ssl=self._ssl
                ) as resp:
                    if resp.status == 410:
                        raise RuntimeError("watch RV expired")
                    resp.raise_for_status()
                    async for ev in self._iter_json_lines(resp.content):
                        obj = ev.get("object") or {}
                        etype = ev.get("type", MODIFIED)
                        if etype == "ERROR":
                            # apiserver reports expired RV as a 200 stream
                            # with an ERROR Status event, then closes
                            raise RuntimeError(
                                f"watch ERROR event: {obj.get('message', obj)}"
                            )
                        obj.setdefault("kind", kind)
                        if etype == "BOOKMARK":
                            rv = (obj.get("metadata") or {}).get(
                                "resourceVersion", rv
                            )
                            continue
                        rv = (obj.get("metadata") or {}).get("resourceVersion", rv)
                        if self._apply(etype, obj):
                            self._emit(etype, obj)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                if self._stopping:
                    return
                logger.warning("watch %s broke (%s); relisting", kind, e)
            # any stream end (error, ERROR event, or server-side close)
            # throttles and relists before reconnecting: deletions missed
            # while disconnected must be purged and the RV refreshed
            if not self._stopping:
                await asyncio.sleep(0.5)
                try:
                    rv = await loop.run_in_executor(None, self._relist, kind)
                except Exception as e2:
                    logger.warning("relist %s failed: %s", kind, e2)
                    rv = ""

    # -- reads (sync, from cache) ---------------------------------------------

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        with self._lock:
            obj = self._cache.get((kind, namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            return copy.deepcopy(obj)

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
        predicate: Optional[Callable[[Dict[str, Any]], bool]] = None,
    ) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for (k, ns, _), obj in self._cache.items():
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if selector and not labels_match(obj, selector):
                    continue
                if predicate and not predicate(obj):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def all_objects(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [copy.deepcopy(o) for o in self._cache.values()]

    # -- writes (blocking HTTP + immediate cache apply) ------------------------

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        kind = obj.get("kind") or ""
        ns = (obj.get("metadata") or {}).get("namespace") or None
        created = self._request("POST", self._collection_path(kind, ns), obj)
        created.setdefault("kind", kind)
        if self._apply(ADDED, created):
            self._emit(ADDED, created)
        return copy.deepcopy(created)

    def update(self, obj: Dict[str, Any], expect_rv: Optional[str] = None) -> Dict[str, Any]:
        kind = obj.get("kind") or ""
        name = obj["metadata"]["name"]
        ns = obj["metadata"].get("namespace") or None
        if expect_rv:
            obj = copy.deepcopy(obj)
            obj["metadata"]["resourceVersion"] = expect_rv
        updated = self._request("PUT", self._object_path(kind, name, ns), obj)
        updated.setdefault("kind", kind)
        gone = updated.get("metadata", {}).get("deletionTimestamp") and not updated.get(
            "metadata", {}
        ).get("finalizers")
        event = DELETED if gone else MODIFIED
        if self._apply(event, updated):
            self._emit(event, updated)
        return copy.deepcopy(updated)

    #: CRD kinds installed with a status subresource (deploy/crds/*.yaml):
    #: the apiserver STRIPS .status from main-resource writes for these, so
    #: status changes must go to the /status subresource path.
    STATUS_SUBRESOURCE_KINDS = frozenset(
        {"InferenceServerConfig", "LauncherConfig", "LauncherPopulationPolicy"}
    )

    def update_status(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        """PUT the /status subresource (spec/metadata changes are ignored
        by the server on this path, mirroring kube semantics)."""
        kind = obj.get("kind") or ""
        name = obj["metadata"]["name"]
        ns = obj["metadata"].get("namespace") or None
        updated = self._request(
            "PUT", self._object_path(kind, name, ns) + "/status", obj
        )
        updated.setdefault("kind", kind)
        if self._apply(MODIFIED, updated):
            self._emit(MODIFIED, updated)
        return copy.deepcopy(updated)

    def mutate(
        self,
        kind: str,
        namespace: str,
        name: str,
        fn: Callable[[Dict[str, Any]], Optional[Dict[str, Any]]],
        retries: int = 8,
    ) -> Dict[str, Any]:
        for _ in range(retries):
            # read FRESH from the server: the cache may trail other writers
            cur = self._request("GET", self._object_path(kind, name, namespace))
            cur.setdefault("kind", kind)
            new = fn(copy.deepcopy(cur))
            if new is None:
                return cur
            try:
                if kind in self.STATUS_SUBRESOURCE_KINDS:
                    # split the write the way the apiserver demands: the
                    # main PUT drops .status, the /status PUT drops the rest
                    def strip(o):
                        return {k: v for k, v in o.items() if k != "status"}

                    out = new
                    if strip(new) != strip(cur):
                        out = self.update(new)
                    if new.get("status") != cur.get("status"):
                        merged = copy.deepcopy(out)
                        merged["status"] = new.get("status")
                        out = self.update_status(merged)
                    return out
                return self.update(new)
            except Conflict:
                continue
        raise Conflict(f"mutate {kind} {namespace}/{name}: retries exhausted")

    def delete(
        self,
        kind: str,
        namespace: str,
        name: str,
        expect_uid: Optional[str] = None,
        expect_rv: Optional[str] = None,
    ) -> None:
        body: Dict[str, Any] = {}
        pre: Dict[str, Any] = {}
        if expect_uid:
            pre["uid"] = expect_uid
        if expect_rv:
            pre["resourceVersion"] = expect_rv
        if pre:
            body["preconditions"] = pre
        result = self._request(
            "DELETE", self._object_path(kind, name, namespace), body or None
        )
        # kube returns the (terminating or final) object, or a Status
        if result.get("kind") not in ("Status", None):
            result.setdefault("kind", kind)
            terminating = result.get("metadata", {}).get("finalizers") and result.get(
                "metadata", {}
            ).get("deletionTimestamp")
            event = MODIFIED if terminating else DELETED
            if self._apply(event, result):
                self._emit(event, result)
        else:
            with self._lock:
                obj = self._cache.pop((kind, namespace, name), None)
            if obj is not None:
                self._emit(DELETED, obj)
