"""A kube-API-shaped in-memory ACID object store with watch.

The reference externalizes all controller state to the Kubernetes API (the
"ACID store", docs/dual-pods.md:396-404) and recovers from restarts by
re-reading it. This store reproduces the API semantics the controllers rely
on:

  * objects are JSON-shaped dicts with `kind` + `metadata` (name, namespace,
    uid, resourceVersion, labels, annotations, finalizers, deletionTimestamp);
  * **optimistic concurrency**: update/delete take optional UID and
    resourceVersion preconditions and raise Conflict on mismatch;
  * **finalizers**: delete marks `deletionTimestamp` and the object stays
    (Terminating) until its finalizer list empties;
  * **watch**: subscribers receive (ADDED | MODIFIED | DELETED, obj) events
    in commit order.

A production deployment implements this same interface against the real kube
API; every consumer (controllers, populator) is store-agnostic.
"""

from __future__ import annotations

import copy
import threading
import time
import uuid as uuidlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class NotFound(Exception):
    pass


class Conflict(Exception):
    """UID/resourceVersion precondition failed or RV is stale."""


class AlreadyExists(Exception):
    pass


def _key(kind: str, namespace: str, name: str) -> Tuple[str, str, str]:
    return (kind, namespace, name)


def meta(obj: Dict[str, Any]) -> Dict[str, Any]:
    return obj.setdefault("metadata", {})


def labels_match(obj: Dict[str, Any], selector: Dict[str, str]) -> bool:
    lab = (obj.get("metadata") or {}).get("labels") or {}
    return all(lab.get(k) == v for k, v in selector.items())


class InMemoryStore:
    def __init__(self) -> None:
        self._objs: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        self._rv = 0
        self._lock = threading.RLock()
        self._watchers: List[Callable[[str, Dict[str, Any]], None]] = []

    # -- internals -----------------------------------------------------------

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _emit(self, event: str, obj: Dict[str, Any]) -> None:
        snapshot = copy.deepcopy(obj)
        for w in list(self._watchers):
            w(event, snapshot)

    # -- watch ---------------------------------------------------------------

    def subscribe(self, handler: Callable[[str, Dict[str, Any]], None]) -> Callable[[], None]:
        """Register a synchronous event handler; returns an unsubscribe fn.
        Handlers run inside the commit (keep them cheap: enqueue only)."""
        self._watchers.append(handler)
        return lambda: self._watchers.remove(handler)

    # -- reads ---------------------------------------------------------------

    def get(self, kind: str, namespace: str, name: str) -> Dict[str, Any]:
        with self._lock:
            obj = self._objs.get(_key(kind, namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            return copy.deepcopy(obj)

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
        predicate: Optional[Callable[[Dict[str, Any]], bool]] = None,
    ) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for (k, ns, _), obj in self._objs.items():
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if selector and not labels_match(obj, selector):
                    continue
                if predicate and not predicate(obj):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    # -- writes --------------------------------------------------------------

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        obj = copy.deepcopy(obj)
        m = meta(obj)
        kind = obj.get("kind") or ""
        if not kind or not m.get("name"):
            raise ValueError("object needs kind and metadata.name")
        ns = m.setdefault("namespace", "")
        with self._lock:
            key = _key(kind, ns, m["name"])
            if key in self._objs:
                raise AlreadyExists(f"{kind} {ns}/{m['name']}")
            m.setdefault("uid", str(uuidlib.uuid4()))
            m["resourceVersion"] = self._next_rv()
            m.setdefault("creationTimestamp", time.time())
            m.setdefault("generation", 1)
            self._objs[key] = obj
            self._emit(ADDED, obj)
            return copy.deepcopy(obj)

    def update(
        self,
        obj: Dict[str, Any],
        expect_rv: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Replace the stored object. If the caller's object carries a
        resourceVersion (or expect_rv is given), it must match (optimistic
        concurrency, as kube enforces)."""
        obj = copy.deepcopy(obj)
        m = meta(obj)
        kind = obj.get("kind") or ""
        ns = m.get("namespace", "")
        with self._lock:
            key = _key(kind, ns, m["name"])
            cur = self._objs.get(key)
            if cur is None:
                raise NotFound(f"{kind} {ns}/{m['name']}")
            cur_rv = cur["metadata"]["resourceVersion"]
            want_rv = expect_rv or m.get("resourceVersion")
            if want_rv and want_rv != cur_rv:
                raise Conflict(
                    f"{kind} {ns}/{m['name']}: rv {want_rv} != {cur_rv}"
                )
            if m.get("uid") and m["uid"] != cur["metadata"]["uid"]:
                raise Conflict(f"{kind} {ns}/{m['name']}: uid mismatch")
            # spec changes bump generation (kube does this for CRs with
            # status subresources; good enough for our consumers)
            if obj.get("spec") != cur.get("spec"):
                m["generation"] = int(cur["metadata"].get("generation", 1)) + 1
            else:
                m["generation"] = cur["metadata"].get("generation", 1)
            m["uid"] = cur["metadata"]["uid"]
            m["creationTimestamp"] = cur["metadata"].get("creationTimestamp")
            if cur["metadata"].get("deletionTimestamp") is not None:
                m["deletionTimestamp"] = cur["metadata"]["deletionTimestamp"]
            m["resourceVersion"] = self._next_rv()
            self._objs[key] = obj
            # a finalizer-clearing update on a terminating object completes
            # the deletion
            if (
                m.get("deletionTimestamp") is not None
                and not m.get("finalizers")
            ):
                del self._objs[key]
                self._emit(DELETED, obj)
                return copy.deepcopy(obj)
            self._emit(MODIFIED, obj)
            return copy.deepcopy(obj)

    def mutate(
        self,
        kind: str,
        namespace: str,
        name: str,
        fn: Callable[[Dict[str, Any]], Optional[Dict[str, Any]]],
        retries: int = 8,
    ) -> Dict[str, Any]:
        """Read-modify-write with automatic Conflict retry. `fn` mutates (or
        returns) the object; return None from fn to abort (returns current)."""
        for _ in range(retries):
            cur = self.get(kind, namespace, name)
            new = fn(copy.deepcopy(cur))
            if new is None:
                return cur
            try:
                return self.update(new)
            except Conflict:
                continue
        raise Conflict(f"mutate {kind} {namespace}/{name}: retries exhausted")

    def delete(
        self,
        kind: str,
        namespace: str,
        name: str,
        expect_uid: Optional[str] = None,
        expect_rv: Optional[str] = None,
    ) -> None:
        """Kube delete semantics: precondition check; with finalizers the
        object enters Terminating (deletionTimestamp set) and is removed only
        once finalizers empty."""
        with self._lock:
            key = _key(kind, namespace, name)
            cur = self._objs.get(key)
            if cur is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            m = cur["metadata"]
            if expect_uid and m["uid"] != expect_uid:
                raise Conflict(f"uid precondition failed for {namespace}/{name}")
            if expect_rv and m["resourceVersion"] != expect_rv:
                raise Conflict(f"rv precondition failed for {namespace}/{name}")
            if m.get("finalizers"):
                if m.get("deletionTimestamp") is None:
                    m["deletionTimestamp"] = time.time()
                    m["resourceVersion"] = self._next_rv()
                    self._emit(MODIFIED, cur)
                return
            del self._objs[key]
            # kube assigns deletion a fresh RV — watch consumers resuming
            # from a list RV must see deletions committed after the list
            m["resourceVersion"] = self._next_rv()
            self._emit(DELETED, cur)

    # -- conveniences --------------------------------------------------------

    def all_objects(self) -> Iterable[Dict[str, Any]]:
        with self._lock:
            return [copy.deepcopy(o) for o in self._objs.values()]
