"""chip-map population tool — the TPU edition of the reference's
`scripts/ensure-nodes-mapped.sh` (gpu-map ConfigMap, controller.go:888-924).

Ensures the ``chip-map`` ConfigMap has a data entry for every schedulable
TPU node: nodes already mapped are left untouched; unmapped nodes are probed
(in production by launching a one-shot pod on the node that runs the
`tpuinfo` shim — native/tpuinfo — and prints the chip table; in tests by an
injected prober) and the result is written in the ChipMap line grammar::

    topology: 2x4
    0 tpu-n1-0-0 0,0
    1 tpu-n1-0-1 0,1
    ...

The hardware-less e2e and real deployments agree on chip identity only
through this map — same role as the reference's gpu-map.
"""

from __future__ import annotations

import argparse
import logging
import subprocess
from typing import Any, Callable, Dict, List, Optional, Union

from ..api import constants as C
from ..parallel.topology import ChipMap, HostTopology
from .store import AlreadyExists

logger = logging.getLogger(__name__)

#: node -> HostTopology, or a single-node ChipMap when the probe carries
#: multi-host identity (origin:/slice:); None = probe failed, skip node
Prober = Callable[[str], Optional[Union[HostTopology, ChipMap]]]


def tpu_nodes(store: Any, selector: Optional[Dict[str, str]] = None) -> List[Dict[str, Any]]:
    """Schedulable nodes that look like TPU hosts: either matching the given
    label selector, or reporting ``google.com/tpu`` capacity."""
    out = []
    for node in store.list("Node", selector=selector or None):
        if (node.get("spec") or {}).get("unschedulable"):
            logger.info(
                "skipping unschedulable node %s", node["metadata"]["name"]
            )
            continue
        if selector:
            out.append(node)
            continue
        capacity = ((node.get("status") or {}).get("capacity")) or {}
        if any("tpu" in k for k in capacity):
            out.append(node)
    return out


def ensure_nodes_mapped(
    store: Any,
    namespace: str,
    prober: Prober,
    selector: Optional[Dict[str, str]] = None,
) -> List[str]:
    """Idempotently fill the chip-map; returns the nodes newly mapped."""
    cm_name = C.CHIP_MAP_CONFIGMAP
    cm = store.try_get("ConfigMap", namespace, cm_name)
    if cm is None:
        try:
            cm = store.create(
                {
                    "kind": "ConfigMap",
                    "metadata": {"name": cm_name, "namespace": namespace},
                    "data": {},
                }
            )
        except AlreadyExists:
            cm = store.get("ConfigMap", namespace, cm_name)

    added: List[str] = []
    for node in tpu_nodes(store, selector):
        name = node["metadata"]["name"]
        if (cm.get("data") or {}).get(name):
            continue  # already mapped: the map is append-only, like gpu-map
        host = prober(name)
        if host is None:
            logger.warning("could not index node %s", name)
            continue
        if isinstance(host, ChipMap):
            # a ChipMap-returning prober carries multi-host identity too
            # (origin:/slice: lines from the tpuinfo table)
            value = host.dump().get(name)
            host = host.host(name)
            if value is None or host is None:
                logger.warning("prober returned a map without node %s", name)
                continue
        else:
            single = ChipMap()
            single.set_host(name, host)
            value = single.dump()[name]

        def apply(obj):
            obj.setdefault("data", {})[name] = value
            return obj

        cm = store.mutate("ConfigMap", namespace, cm_name, apply)
        added.append(name)
        logger.info("mapped node %s (%d chips)", name, len(host.chips))
    return added


def kubectl_tpuinfo_prober(
    image: str, namespace: str, kubectl: str = "kubectl"
) -> Prober:
    """Production prober: run a one-shot pod pinned to the node that executes
    the tpuinfo shim (`fma-tpuinfo --table`) and parse its log — the same
    choreography as ensure-nodes-mapped.sh's nvidia-smi pod."""

    def probe(node: str) -> Optional[ChipMap]:
        pod = f"{node}-chip-map"
        manifest = f"""
apiVersion: v1
kind: Pod
metadata:
  name: {pod}
  labels: {{app: gather-chip-map}}
spec:
  restartPolicy: OnFailure
  nodeSelector: {{kubernetes.io/hostname: "{node}"}}
  containers:
  - name: c1
    image: {image}
    command: ["python", "-m", "llm_d_fast_model_actuation_tpu.native.tpuinfo", "--table"]
"""
        try:
            subprocess.run(
                [kubectl, "-n", namespace, "create", "-f", "-"],
                input=manifest.encode(),
                check=True,
            )
            subprocess.run(
                [
                    kubectl, "-n", namespace, "wait", f"pod/{pod}",
                    "--for", "jsonpath={.status.phase}=Succeeded",
                    "--timeout", "120s",
                ],
                check=True,
            )
            logs = subprocess.run(
                [kubectl, "-n", namespace, "logs", pod],
                check=True,
                capture_output=True,
            ).stdout.decode()
            cm = ChipMap.parse({node: logs})
            if cm.host(node) is None:
                return None
            # return the whole single-node map: origin:/slice: lines (the
            # multi-host gang planner's input) survive the round-trip
            return cm
        except (subprocess.CalledProcessError, ValueError, KeyError) as e:
            logger.warning("probe of %s failed: %s", node, e)
            return None
        finally:
            subprocess.run(
                [kubectl, "-n", namespace, "delete", "pod", pod,
                 "--ignore-not-found"],
                check=False,
            )

    return probe


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="fma-ensure-nodes-mapped",
        description="populate the chip-map ConfigMap for unmapped TPU nodes",
    )
    p.add_argument("--namespace", default="default")
    p.add_argument("--api-base", default="", help="apiserver base URL (default: in-cluster)")
    p.add_argument(
        "--node-selector",
        default="",
        help="label selector key=value[,k=v] for TPU nodes "
        "(default: nodes with tpu capacity)",
    )
    p.add_argument(
        "--tpuinfo-image",
        default="ghcr.io/llm-d/fma-tpu-launcher:latest",
        help="image containing the fma-tpuinfo shim for probe pods",
    )
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from .kubestore import KubeStore

    if args.api_base:
        store = KubeStore(args.api_base, args.namespace, kinds=None)
    else:
        store = KubeStore.in_cluster(args.namespace)
    # one-shot tool: a plain relist is enough, no watch loops
    store._relist("Node")
    store._relist("ConfigMap")

    selector = None
    if args.node_selector:
        selector = dict(kv.split("=", 1) for kv in args.node_selector.split(","))
    prober = kubectl_tpuinfo_prober(args.tpuinfo_image, args.namespace)
    added = ensure_nodes_mapped(store, args.namespace, prober, selector)
    print(f"mapped {len(added)} node(s): {', '.join(added) or '(none)'}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
