"""llm-d-fast-model-actuation for TPU — a TPU-native fast-model-actuation framework.

A ground-up re-design, for TPU hardware, of the capabilities of
`llm-d-incubation/llm-d-fast-model-actuation` (the "reference"):

* an **inference engine** stratum (the reference delegates this to vLLM+CUDA;
  here it is JAX/XLA/Pallas-native: bf16 matmuls on the MXU, paged KV cache,
  ``jit``-compiled prefill/decode, ``jax.sharding.Mesh`` TP/DP/SP over ICI),
* **level-1 sleep/wake**: live model tensors move HBM <-> pinned host memory
  via XLA memory kinds without killing the serving process
  (reference: vLLM sleep mode, ``README.md:16-26``),
* a **launcher** that preloads JAX/libtpu and spawns/kills engine instances
  via a REST API (reference: ``inference_server/launcher/launcher.py``),
* the **dual-pods** control plane: server-requesting / server-providing Pod
  pairing, binding state machine, sleeper budget, launcher population policy
  (reference: ``pkg/controller/dual-pods``, ``pkg/controller/launcher-populator``).

Import alias: ``import llm_d_fast_model_actuation_tpu as fma_tpu``.
"""

__version__ = "0.1.0"
