"""Probes server: /ready -> 200/503 from the controller-set readiness bool
(reference: pkg/server/requester/probes/server.go:38-87). This is what the
kubelet's readiness probe hits, turning controller relays into Pod Ready
condition flips that HPA/EPP/users observe."""

from __future__ import annotations

from aiohttp import web

from ..api import spi as spiapi
from .spi import ReadyFlag


class ProbesServer:
    def __init__(self, ready_flag: ReadyFlag) -> None:
        self.ready = ready_flag

    def build_app(self) -> web.Application:
        app = web.Application()

        async def ready(request: web.Request) -> web.Response:
            if self.ready.get():
                return web.Response(text="ready\n")
            return web.Response(status=503, text="not ready\n")

        app.router.add_get(spiapi.READY_PATH, ready)
        return app
