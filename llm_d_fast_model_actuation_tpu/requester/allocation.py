"""ConfigMap-backed chip allocation for the hardware-less test-requester.

The reference's test-requester emulates scheduler/device-plugin contention
with an optimistic-concurrency ConfigMap loop
(cmd/test-requester/gpu-allocation.go:41-257): every requester pod claims N
accelerators on its node from a shared ConfigMap, retrying on write
conflicts, and releases its claims on exit. This is what makes multi-
requester contention on one node testable without hardware.

TPU edition: the ``chip-allocations`` ConfigMap holds, per node, a JSON map
``chip_id -> holder pod name``. `ChipAllocator.allocate` CAS-loops:

  1. read the ConfigMap fresh (never from a cache),
  2. pick the lexically-first free chips (deterministic given a snapshot),
  3. write back with a resourceVersion precondition — a concurrent claimer
     triggers Conflict and we re-read (their claim now visible).

Losing a race therefore never double-books: the loser sees the winner's
claim on retry and picks other chips, or waits for capacity.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

ALLOCATIONS_CONFIGMAP = "chip-allocations"


class OutOfChips(TimeoutError):
    """Not enough free chips appeared before the deadline."""


class ChipAllocator:
    def __init__(
        self,
        store: Any,  # KubeStore-compatible: try_get/create/mutate (fresh reads)
        namespace: str,
        node: str,
        holder: str,  # this requester pod's name
    ) -> None:
        self.store = store
        self.namespace = namespace
        self.node = node
        self.holder = holder

    # -- ConfigMap plumbing --------------------------------------------------

    def _ensure_cm(self) -> None:
        from ..controller.store import AlreadyExists

        if self.store.try_get("ConfigMap", self.namespace, ALLOCATIONS_CONFIGMAP):
            return
        try:
            self.store.create(
                {
                    "kind": "ConfigMap",
                    "metadata": {
                        "name": ALLOCATIONS_CONFIGMAP,
                        "namespace": self.namespace,
                    },
                    "data": {},
                }
            )
        except AlreadyExists:
            pass

    @staticmethod
    def _node_claims(cm: Dict[str, Any], node: str) -> Dict[str, str]:
        raw = (cm.get("data") or {}).get(node) or "{}"
        try:
            return {str(k): str(v) for k, v in json.loads(raw).items()}
        except json.JSONDecodeError:
            return {}

    # -- the allocation loop -------------------------------------------------

    def allocate(
        self,
        count: int,
        pool: List[str],
        timeout_s: float = 60.0,
        poll_s: float = 0.2,
        should_stop=None,
    ) -> List[str]:
        """Claim `count` chips of `pool` on this node; blocks (polling) while
        capacity is taken by other holders. Idempotent: existing claims by
        this holder count toward `count` (crash-restart safe).
        `should_stop()` (e.g. a SIGTERM flag) aborts the wait promptly."""
        self._ensure_cm()
        deadline = time.monotonic() + timeout_s
        while True:
            if should_stop is not None and should_stop():
                raise OutOfChips(f"{self.holder}: allocation aborted (stopping)")
            got: Optional[List[str]] = None

            def apply(cm: Dict[str, Any]) -> Optional[Dict[str, Any]]:
                nonlocal got
                claims = self._node_claims(cm, self.node)
                mine = sorted(c for c, h in claims.items() if h == self.holder)
                if len(mine) >= count:
                    got = mine[:count]
                    return None  # nothing to write
                free = sorted(
                    c for c in pool if c not in claims
                )
                need = count - len(mine)
                if len(free) < need:
                    got = None
                    return None  # not enough capacity in THIS snapshot
                take = free[:need]
                for c in take:
                    claims[c] = self.holder
                cm.setdefault("data", {})[self.node] = json.dumps(
                    claims, sort_keys=True
                )
                got = mine + take
                return cm

            # mutate = fresh-read + rv-preconditioned write + conflict retry
            self.store.mutate(
                "ConfigMap", self.namespace, ALLOCATIONS_CONFIGMAP, apply
            )
            if got is not None:
                logger.info(
                    "allocated %s on %s for %s", got, self.node, self.holder
                )
                return got
            if time.monotonic() > deadline:
                raise OutOfChips(
                    f"{self.holder}: {count} chip(s) on {self.node} not free "
                    f"within {timeout_s}s"
                )
            time.sleep(poll_s)

    def release(self) -> None:
        """Drop every claim held by this holder (exit path)."""

        def apply(cm: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            claims = self._node_claims(cm, self.node)
            kept = {c: h for c, h in claims.items() if h != self.holder}
            if kept == claims:
                return None
            cm.setdefault("data", {})[self.node] = json.dumps(
                kept, sort_keys=True
            )
            return cm

        try:
            self.store.mutate(
                "ConfigMap", self.namespace, ALLOCATIONS_CONFIGMAP, apply
            )
            logger.info("released claims of %s on %s", self.holder, self.node)
        except Exception:
            logger.exception("release failed (claims will leak until GC)")
