"""Requester stub main: SPI + probes servers in one process.

Reference parity: cmd/requester/main.go:32-85 (real chips) and
cmd/test-requester (emulated allocation for hardware-less e2e). Backends:

  * ``--backend real``   — chips from the native tpuinfo shim (or /dev/accel
    fallback), HBM usage from the shim;
  * ``--backend env``    — chips from $TPU_VISIBLE_DEVICES + a chip-map file
    (what the kube scheduler/device plugin would have granted);
  * ``--backend static`` — explicit ``--chips a,b,c`` (tests).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
from typing import Dict, List

from aiohttp import web

from .probes import ProbesServer
from .spi import LogSink, ReadyFlag, SpiServer

logger = logging.getLogger(__name__)


def resolve_chips(args: argparse.Namespace) -> List[str]:
    if args.backend == "static":
        return [c for c in args.chips.split(",") if c]
    if args.backend == "env":
        from ..parallel.topology import ChipMap
        import json

        node = os.environ.get("NODE_NAME", "")
        path = args.chip_map_path or os.environ.get("CHIP_MAP_PATH", "")
        visible = os.environ.get("TPU_VISIBLE_DEVICES", "")
        if not (node and path and visible):
            raise RuntimeError(
                "env backend needs NODE_NAME, CHIP_MAP_PATH and TPU_VISIBLE_DEVICES"
            )
        with open(path) as f:
            cm = ChipMap.parse(json.load(f))
        host = cm.host(node)
        if host is None:
            raise RuntimeError(f"node {node} not in chip map")
        want = {int(i) for i in visible.split(",")}
        return [c.chip_id for c in host.chips if c.index in want]
    # real
    from ..launcher.chiptranslator import _enumerate_real

    return [c.chip_id for c in _enumerate_real().chips]


def memory_backend(args: argparse.Namespace, chip_ids: List[str]):
    if args.backend == "real":
        def usage() -> Dict[str, int]:
            from ..native import tpuinfo

            all_usage = tpuinfo.hbm_usage()
            return {c: all_usage.get(c, 0) for c in chip_ids}

        return usage
    return lambda: {c: 0 for c in chip_ids}


async def serve(args: argparse.Namespace) -> None:
    ready = ReadyFlag(False)
    sink = LogSink()
    chips = resolve_chips(args)
    logger.info("requester stub: chips=%s", chips)
    spi = SpiServer(chips, ready, memory_backend(args, chips), sink)
    probes = ProbesServer(ready)

    runners = []
    for app, port in ((spi.build_app(), args.spi_port), (probes.build_app(), args.probes_port)):
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, args.host, port)
        await site.start()
        runners.append(runner)
    logger.info("SPI on :%s, probes on :%s", args.spi_port, args.probes_port)
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        for runner in runners:
            await runner.cleanup()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="fma-tpu-requester")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument(
        "--spi-port", type=int, default=int(os.environ.get("SPI_PORT", "8081"))
    )
    p.add_argument(
        "--probes-port",
        type=int,
        default=int(os.environ.get("PROBES_PORT", "8080")),
    )
    p.add_argument("--backend", choices=("real", "env", "static"), default="real")
    p.add_argument("--chips", default="", help="comma-separated chip IDs (static)")
    p.add_argument("--chip-map-path", default="")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    asyncio.run(serve(args))


if __name__ == "__main__":
    main()
