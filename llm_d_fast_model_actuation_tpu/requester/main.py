"""Requester stub main: SPI + probes servers in one process.

Reference parity: cmd/requester/main.go:32-85 (real chips) and
cmd/test-requester (emulated allocation for hardware-less e2e). Backends:

  * ``--backend real``   — chips from the native tpuinfo shim (or /dev/accel
    fallback), HBM usage from the shim;
  * ``--backend env``    — chips from $TPU_VISIBLE_DEVICES + a chip-map file
    (what the kube scheduler/device plugin would have granted);
  * ``--backend static`` — explicit ``--chips a,b,c`` (tests);
  * ``--backend alloc``  — claim ``--alloc-count`` chips of ``--chips`` on
    ``--node`` from the shared ``chip-allocations`` ConfigMap with the
    optimistic-concurrency loop (reference test-requester contention
    emulation, cmd/test-requester/gpu-allocation.go:41-257); claims are
    released on shutdown.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
from typing import Dict, List

from aiohttp import web

from .probes import ProbesServer
from .spi import LogSink, ReadyFlag, SpiServer

logger = logging.getLogger(__name__)


def resolve_chips(args: argparse.Namespace, should_stop=None):
    """Returns (chip_ids, cleanup_fn_or_None)."""
    if args.backend == "static":
        return [c for c in args.chips.split(",") if c], None
    if args.backend == "alloc":
        from ..controller.kubestore import KubeStore
        from .allocation import ChipAllocator

        pool = [c for c in args.chips.split(",") if c]
        if not (args.api_base and args.node and pool and args.alloc_count > 0):
            raise RuntimeError(
                "alloc backend needs --api-base, --node, --chips (the node "
                "pool) and --alloc-count"
            )
        store = KubeStore(args.api_base, args.namespace, kinds=None)
        holder = args.pod_name or os.environ.get("POD_NAME") or f"req-{os.getpid()}"
        alloc = ChipAllocator(store, args.namespace, args.node, holder)
        try:
            chips = alloc.allocate(
                args.alloc_count,
                pool,
                timeout_s=args.alloc_timeout,
                should_stop=should_stop,
            )
        except Exception:
            alloc.release()  # never leak a partial/prior claim on failure
            raise
        return chips, alloc.release
    if args.backend == "env":
        from ..parallel.topology import ChipMap
        import json

        node = os.environ.get("NODE_NAME", "")
        path = args.chip_map_path or os.environ.get("CHIP_MAP_PATH", "")
        visible = os.environ.get("TPU_VISIBLE_DEVICES", "")
        if not (node and path and visible):
            raise RuntimeError(
                "env backend needs NODE_NAME, CHIP_MAP_PATH and TPU_VISIBLE_DEVICES"
            )
        with open(path) as f:
            cm = ChipMap.parse(json.load(f))
        host = cm.host(node)
        if host is None:
            raise RuntimeError(f"node {node} not in chip map")
        want = {int(i) for i in visible.split(",")}
        return [c.chip_id for c in host.chips if c.index in want], None
    # real
    from ..launcher.chiptranslator import _enumerate_real

    return [c.chip_id for c in _enumerate_real().chips], None


def memory_backend(args: argparse.Namespace, chip_ids: List[str]):
    if args.backend == "real":
        def usage() -> Dict[str, int]:
            from ..native import tpuinfo

            all_usage = tpuinfo.hbm_usage()
            return {c: all_usage.get(c, 0) for c in chip_ids}

        return usage
    return lambda: {c: 0 for c in chip_ids}


async def serve(args: argparse.Namespace) -> None:
    # SIGTERM must run the cleanup path — the alloc backend's ConfigMap
    # claims are released on exit (gpu-allocation.go's defer-release
    # equivalent) — so install handlers BEFORE the allocation runs (which is
    # pushed to a thread below so the loop stays responsive to the signal).
    import signal

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass

    ready = ReadyFlag(False)
    sink = LogSink()
    # the alloc backend blocks (CAS polling up to --alloc-timeout): run it in
    # a thread so the installed SIGTERM handler can actually fire mid-wait
    alloc_task = asyncio.create_task(
        asyncio.to_thread(resolve_chips, args, stop.is_set)
    )
    stop_task = asyncio.create_task(stop.wait())
    done, _ = await asyncio.wait(
        {alloc_task, stop_task}, return_when=asyncio.FIRST_COMPLETED
    )
    if stop_task in done and alloc_task not in done:
        # terminated while waiting for chips: the allocator sees stop on its
        # next poll, releases anything it won, and raises
        try:
            await alloc_task
        except Exception:
            pass
        return
    stop_task.cancel()
    chips, cleanup = await alloc_task
    logger.info("requester stub: chips=%s", chips)
    runners = []
    try:
        spi = SpiServer(chips, ready, memory_backend(args, chips), sink)
        probes = ProbesServer(ready)
        for app, port in (
            (spi.build_app(), args.spi_port),
            (probes.build_app(), args.probes_port),
        ):
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, args.host, port)
            await site.start()
            runners.append(runner)
        logger.info("SPI on :%s, probes on :%s", args.spi_port, args.probes_port)
        await stop.wait()
    finally:
        # covers server-startup failures too: a claim must never outlive
        # the process that holds it
        for runner in runners:
            await runner.cleanup()
        if cleanup is not None:
            cleanup()  # release ConfigMap chip claims (alloc backend)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="fma-tpu-requester")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument(
        "--spi-port", type=int, default=int(os.environ.get("SPI_PORT", "8081"))
    )
    p.add_argument(
        "--probes-port",
        type=int,
        default=int(os.environ.get("PROBES_PORT", "8080")),
    )
    p.add_argument(
        "--backend", choices=("real", "env", "static", "alloc"), default="real"
    )
    p.add_argument(
        "--chips",
        default="",
        help="comma-separated chip IDs (static: owned outright; "
        "alloc: the node's contended pool)",
    )
    p.add_argument("--chip-map-path", default="")
    # alloc backend (ConfigMap-based contention emulation)
    p.add_argument("--api-base", default="", help="apiserver base URL")
    p.add_argument("--namespace", default="default")
    p.add_argument("--node", default=os.environ.get("NODE_NAME", ""))
    p.add_argument("--pod-name", default=os.environ.get("POD_NAME", ""))
    p.add_argument("--alloc-count", type=int, default=1)
    p.add_argument("--alloc-timeout", type=float, default=60.0)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    asyncio.run(serve(args))


if __name__ == "__main__":
    main()
