"""The requester stub: the process inside a server-requesting Pod.

The requesting Pod holds the TPU allocation in the scheduler's eyes but does
no inference; this stub (reference: `cmd/requester`, `pkg/server/requester`)
serves two HTTP planes:

  * **SPI server** (port $SPI_PORT, default 8081) — the dual-pods controller's
    window into the Pod: which chips the Pod was allocated, their HBM usage,
    readiness setters, and a relayed-log sink;
  * **probes server** (port $PROBES_PORT, default 8080) — `/ready` backed by
    the controller-set readiness bool; the kubelet's readiness probe target,
    which is how engine readiness is relayed to everything watching the Pod.
"""

from .spi import LogSink, SpiServer  # noqa: F401
from .probes import ProbesServer  # noqa: F401
