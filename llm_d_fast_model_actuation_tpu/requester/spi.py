"""Requester SPI server (reference: pkg/server/requester/coordination).

Paths per `api/spi.py`. The accelerator backend is pluggable:
  * real: the native tpuinfo shim (chip IDs + per-chip HBM bytes);
  * test: a provided chip list + usage callable (the reference's
    `test-requester` emulates scheduler allocation the same way).

The log sink implements the reference's exact chunk protocol
(coordination/server.go:152-209): orderly dedup by absolute start position —
only bytes past the current end are appended; a chunk starting beyond the
end is a 400.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

from aiohttp import web

from ..api import spi as spiapi
from ..utils import tracing


class LogSink:
    """Relayed-log accumulator with overlap dedup by start position."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._lock = threading.Lock()

    @property
    def length(self) -> int:
        return len(self._buf)

    def content(self) -> bytes:
        with self._lock:
            return bytes(self._buf)

    def add_chunk(self, start_pos: int, chunk: bytes) -> tuple:
        """Returns (http_status, message)."""
        with self._lock:
            next_pos = len(self._buf)
            if start_pos < 0:
                return 400, f"Starting position {start_pos} is unacceptable because it is negative"
            if start_pos > next_pos:
                return (
                    400,
                    f"Starting position {start_pos} is beyond the current "
                    f"contentLength={next_pos}",
                )
            if start_pos + len(chunk) <= next_pos:
                return (
                    200,
                    f"Accepted startPos={start_pos}, chunkLength={len(chunk)}, "
                    f"but that has nothing new; still contentLength={next_pos}",
                )
            news = chunk[next_pos - start_pos :] if start_pos < next_pos else chunk
            self._buf.extend(news)
            return (
                200,
                f"Accepted startPos={start_pos}, chunkLength={len(chunk)}; "
                f"addedContentLength={len(news)}, new contentLength={len(self._buf)}",
            )


class SpiServer:
    def __init__(
        self,
        chip_ids: Sequence[str],
        ready_flag: "ReadyFlag",
        memory_usage: Optional[Callable[[], Dict[str, int]]] = None,
        log_sink: Optional[LogSink] = None,
    ) -> None:
        self.chip_ids = list(chip_ids)
        self.ready = ready_flag
        self.memory_usage = memory_usage or (lambda: {c: 0 for c in self.chip_ids})
        self.log_sink = log_sink or LogSink()

    def build_app(self) -> web.Application:
        app = web.Application()

        async def accelerators(request: web.Request) -> web.Response:
            return web.json_response(self.chip_ids)

        async def accel_memory(request: web.Request) -> web.Response:
            try:
                usage = self.memory_usage()
            except Exception as e:
                return web.Response(status=500, text=str(e))
            return web.json_response({k: int(v) for k, v in usage.items()})

        async def become_ready(request: web.Request) -> web.Response:
            # the readiness relay closes the actuation envelope the
            # controller measures — record it as a span of THAT trace
            # (the controller's traceparent rides the SPI call)
            with tracing.span(
                "spi.become_ready",
                parent=tracing.context_from_headers(request.headers),
            ):
                self.ready.set(True)
            return web.Response(text="OK\n")

        async def become_unready(request: web.Request) -> web.Response:
            with tracing.span(
                "spi.become_unready",
                parent=tracing.context_from_headers(request.headers),
            ):
                self.ready.set(False)
            return web.Response(text="OK\n")

        async def set_log(request: web.Request) -> web.Response:
            start_raw = request.query.get(spiapi.LOG_START_POS_PARAM)
            if not start_raw:
                return web.Response(
                    status=400,
                    text=f"Missing {spiapi.LOG_START_POS_PARAM} parameter\n",
                )
            try:
                start_pos = int(start_raw)
            except ValueError as e:
                return web.Response(
                    status=400,
                    text=f"Failed to parse {start_raw!r} as an int: {e}\n",
                )
            chunk = await request.read()
            status, message = self.log_sink.add_chunk(start_pos, chunk)
            return web.Response(status=status, text=message + "\r\n")

        app.router.add_get(spiapi.ACCELERATOR_QUERY_PATH, accelerators)
        app.router.add_get(spiapi.ACCELERATOR_MEMORY_QUERY_PATH, accel_memory)
        app.router.add_post(spiapi.BECOME_READY_PATH, become_ready)
        app.router.add_post(spiapi.BECOME_UNREADY_PATH, become_unready)
        app.router.add_post(spiapi.SET_LOG_PATH, set_log)
        return app


class ReadyFlag:
    """Atomic readiness bool shared between the SPI and probes servers."""

    def __init__(self, initial: bool = False) -> None:
        self._val = initial
        self._lock = threading.Lock()

    def set(self, value: bool) -> None:
        with self._lock:
            self._val = value

    def get(self) -> bool:
        with self._lock:
            return self._val
