"""Benchmark scenarios (reference: inference_server/benchmark/scenarios.py).

  baseline    — cold deploy N pairs, measure T_actuation (scenarios.py:26+)
  scaling     — scale up, down to 1, up again; the second scale-up should be
                warm/hot hits against sleeping instances (hit-rate tracking)
  new_variant — switch through a sequence of model configs on the same
                chips, measuring each switch (the dual-pods headline: model
                change in seconds)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .harness import ActuationBenchmark, BenchmarkConfig, ScenarioReport


async def run_baseline(
    n_pairs: int = 4, cfg: Optional[BenchmarkConfig] = None
) -> Dict[str, Any]:
    cfg = cfg or BenchmarkConfig()
    async with ActuationBenchmark(cfg) as bench:
        report = ScenarioReport("baseline", cfg.mode, cfg.time_scale)
        bench.deploy_config("baseline-model")
        for i in range(n_pairs):
            report.pairs.append(
                await bench.actuate("baseline-model", chips=[f"chip-{i}"])
            )
        return report.summary()


async def run_scaling(
    n_up: int = 4, cfg: Optional[BenchmarkConfig] = None
) -> Dict[str, Any]:
    cfg = cfg or BenchmarkConfig()
    async with ActuationBenchmark(cfg) as bench:
        report = ScenarioReport("scaling", cfg.mode, cfg.time_scale)
        bench.deploy_config("scale-model")

        first_up = [
            await bench.actuate("scale-model", chips=[f"chip-{i}"])
            for i in range(n_up)
        ]
        await bench.scale_down(keep=1)
        second_up = [
            await bench.actuate("scale-model", chips=[f"chip-{i}"])
            for i in range(1, n_up)
        ]
        report.pairs = second_up  # hit-rate is about the RE-scale-up
        report.extra = {
            "first_up_cold": sum(1 for p in first_up if p.path == "cold"),
            "second_up_warm_or_hot": sum(
                1 for p in second_up if p.path in ("warm", "hot")
            ),
        }
        return report.summary()


async def run_new_variant(
    models: Optional[List[str]] = None, cfg: Optional[BenchmarkConfig] = None
) -> Dict[str, Any]:
    models = models or ["llama-3-8b", "qwen-0.5b", "tinyllama-1.1b"]
    cfg = cfg or BenchmarkConfig()
    async with ActuationBenchmark(cfg) as bench:
        report = ScenarioReport("new_variant", cfg.mode, cfg.time_scale)
        # one port per variant: same-port instances on one launcher conflict
        # (a sleeping engine still holds its port), so same-port variants
        # would reclaim each other instead of sleeping side by side
        for i, m in enumerate(models):
            bench.deploy_config(m, port=8000 + i)
        # switch through variants on the same chip set: each switch deletes
        # the old requester and actuates the next model
        for i, m in enumerate(models):
            if i > 0:
                await bench.scale_down(keep=0)
            report.pairs.append(await bench.actuate(m, chips=["chip-0"]))
        # a second full cycle: every variant now has a sleeping instance
        cycle2: List[Any] = []
        for m in models:
            await bench.scale_down(keep=0)
            cycle2.append(await bench.actuate(m, chips=["chip-0"]))
        report.extra = {
            "cycle2_warm_or_hot": sum(1 for p in cycle2 if p.path in ("warm", "hot")),
            "cycle2_pairs": len(cycle2),
        }
        report.pairs.extend(cycle2)
        return report.summary()


async def run_all(
    cfg: Optional[BenchmarkConfig] = None, pairs: int = 4
) -> Dict[str, Any]:
    return {
        "baseline": await run_baseline(pairs, cfg=cfg),
        "scaling": await run_scaling(pairs, cfg=cfg),
        "new_variant": await run_new_variant(cfg=cfg),
    }


def main(argv=None) -> None:
    import argparse
    import asyncio
    import json

    p = argparse.ArgumentParser(prog="fma-tpu-benchmark")
    p.add_argument(
        "--scenario",
        choices=["baseline", "scaling", "new_variant", "all"],
        default="all",
    )
    p.add_argument(
        "--pairs",
        type=int,
        default=4,
        help="pair count for baseline/scaling (new_variant is sized by its model list)",
    )
    p.add_argument("--time-scale", type=float, default=0.01)
    p.add_argument(
        "--mode",
        choices=["simulated", "live"],
        default="simulated",
        help="simulated = in-process fakes with scaled latencies; live = "
        "measure a running stack over HTTP (see --api-base et al.)",
    )
    p.add_argument("--api-base", default="", help="live: apiserver base URL")
    p.add_argument("--namespace", default="bench")
    p.add_argument("--node", default="n1")
    p.add_argument("--spi-port", type=int, default=0, help="live: requester stub SPI port")
    p.add_argument("--probes-port", type=int, default=0, help="live: requester stub probes port")
    args = p.parse_args(argv)

    if args.mode == "live":
        from .live import LiveConfig, run_baseline_live

        if not (args.api_base and args.spi_port and args.probes_port):
            p.error("--mode live needs --api-base, --spi-port, --probes-port")
        if args.scenario not in ("baseline", "all") or args.pairs != 4:
            p.error(
                "--mode live currently measures the baseline scenario only "
                "(cold -> warm); --scenario/--pairs do not apply"
            )
        report = asyncio.run(
            run_baseline_live(
                LiveConfig(
                    api_base=args.api_base,
                    namespace=args.namespace,
                    node=args.node,
                    spi_port=args.spi_port,
                    probes_port=args.probes_port,
                )
            )
        )
        print(json.dumps(report.summary(), indent=2))
        return

    cfg = BenchmarkConfig(time_scale=args.time_scale)
    if args.scenario == "baseline":
        out = asyncio.run(run_baseline(args.pairs, cfg))
    elif args.scenario == "scaling":
        out = asyncio.run(run_scaling(args.pairs, cfg))
    elif args.scenario == "new_variant":
        out = asyncio.run(run_new_variant(cfg=cfg))
    else:
        out = asyncio.run(run_all(cfg, args.pairs))
    print(json.dumps(out, indent=2))


if __name__ == "__main__":  # pragma: no cover
    main()
