from .scenarios import main

main()
