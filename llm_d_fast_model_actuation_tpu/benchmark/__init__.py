"""Actuation benchmark harness (reference: inference_server/benchmark/).

Measures the measurement model of `benchmark.md:24-133`: T_actuation,
T_wake, Hot/Warm hit rates, T_cold_launcher, T_instance_create across the
baseline / scaling / new-variant scenarios, in `simulated` mode (in-memory
control plane + latency-injected fakes) or against a live stack.
"""

from .fleet import (
    Arrival,
    FleetTrafficConfig,
    generate_arrivals,
    trace_digest,
)
from .harness import ActuationBenchmark, BenchmarkConfig
from .scenarios import run_baseline, run_new_variant, run_scaling

__all__ = [
    "ActuationBenchmark",
    "Arrival",
    "BenchmarkConfig",
    "FleetTrafficConfig",
    "generate_arrivals",
    "run_baseline",
    "run_scaling",
    "run_new_variant",
    "trace_digest",
]
