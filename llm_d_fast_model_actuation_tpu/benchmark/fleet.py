"""Fleet traffic model: deterministic open-loop multi-tenant arrivals.

The north-star workload (ROADMAP item 2) is many models on few chips under
heavy, *skewed* traffic: popularity follows a Zipf law (a few hot models
take most requests; "Towards Multi-Model LLM Schedulers" measures exactly
this shape), and load arrives in bursts, not a steady stream. This module
generates that arrival process ahead of time from an explicit seed so a
run is reproducible end to end:

- **Open loop**: arrival times are drawn from a piecewise-homogeneous
  Poisson process (exponential gaps at the phase's rate) and never depend
  on service completions — a slow server builds queue, it does not slow
  the offered load (the closed-loop fallacy every serving benchmark warns
  about).
- **Bursty phases**: the rate alternates ``base_rate_rps`` /
  ``burst_rate_rps`` every ``phase_s`` seconds, and each burst phase
  rotates a different "hot" model whose popularity is boosted — the
  diurnal/hotspot shape that forces actuations instead of letting one
  resident model absorb everything.
- **Zipf popularity**: model ``i`` draws with weight ``1/(i+1)**zipf_s``
  outside bursts.

Everything is ``random.Random(seed)`` (stdlib, platform-stable): the same
config MUST produce the identical trace on every machine — CI asserts it,
and ``trace_digest`` gives the one-line fingerprint benches embed in their
result JSON.

Consumed by ``bench.py fleet`` (the load harness over a live launcher) and
by tests; it deliberately has no HTTP or jax dependencies.
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from dataclasses import asdict, dataclass, field
from typing import List, Sequence


@dataclass(frozen=True)
class FleetTrafficConfig:
    """Knobs of the synthetic multi-tenant arrival process. All fields
    feed the deterministic generator — two equal configs (seed included)
    produce byte-identical traces."""

    seed: int = 0
    num_models: int = 3
    duration_s: float = 12.0
    #: offered load outside / inside burst phases (requests per second,
    #: summed over all models — open loop)
    base_rate_rps: float = 6.0
    burst_rate_rps: float = 18.0
    #: phase length; phases alternate base, burst, base, burst, ...
    phase_s: float = 3.0
    #: Zipf skew exponent for model popularity (0 = uniform)
    zipf_s: float = 1.1
    #: during a burst phase this fraction of draws goes to the phase's
    #: rotating hot model, the rest to the Zipf base distribution
    burst_hot_frac: float = 0.6
    #: sibling-heavy trace mode: when > 1, every draw (burst hot-model
    #: rotation included) lands uniformly in the hot set — models
    #: ``[0, hot_set_size)`` — and the remaining models get zero traffic.
    #: This is the workload co-residency serves with ZERO actuations (all
    #: hot variants device-resident at once); the default (1) leaves the
    #: Zipf/burst process untouched, so existing seeded trace digests are
    #: unchanged.
    hot_set_size: int = 1
    #: per-request shape (token ids drawn uniformly from [1, vocab))
    prompt_len_min: int = 4
    prompt_len_max: int = 12
    max_tokens_min: int = 4
    max_tokens_max: int = 8
    vocab: int = 400


@dataclass(frozen=True)
class Arrival:
    """One precomputed request of the open-loop trace."""

    #: offset from trace start, seconds
    t_s: float
    #: model index in [0, num_models)
    model: int
    prompt: tuple = field(default_factory=tuple)
    max_tokens: int = 4


def _zipf_weights(n: int, s: float) -> List[float]:
    w = [1.0 / ((i + 1) ** s) for i in range(n)]
    total = sum(w)
    return [x / total for x in w]


def _pick(rng: random.Random, weights: Sequence[float]) -> int:
    x = rng.random()
    acc = 0.0
    for i, w in enumerate(weights):
        acc += w
        if x < acc:
            return i
    return len(weights) - 1


def generate_arrivals(cfg: FleetTrafficConfig) -> List[Arrival]:
    """Precompute the whole arrival trace for ``cfg``. Deterministic:
    equal configs yield identical traces (the bench's seeded-CI
    contract)."""
    if cfg.num_models < 1:
        raise ValueError("num_models must be >= 1")
    if cfg.duration_s <= 0:
        raise ValueError("duration_s must be > 0")
    if cfg.phase_s <= 0:
        raise ValueError("phase_s must be > 0")
    if not (0.0 <= cfg.burst_hot_frac <= 1.0):
        raise ValueError("burst_hot_frac must be in [0, 1]")
    if cfg.prompt_len_min < 1 or cfg.prompt_len_max < cfg.prompt_len_min:
        raise ValueError("bad prompt_len range")
    if cfg.max_tokens_min < 1 or cfg.max_tokens_max < cfg.max_tokens_min:
        raise ValueError("bad max_tokens range")
    if not (1 <= cfg.hot_set_size <= cfg.num_models):
        raise ValueError("hot_set_size must be in [1, num_models]")
    rng = random.Random(cfg.seed)
    base_w = _zipf_weights(cfg.num_models, cfg.zipf_s)
    out: List[Arrival] = []
    t = 0.0
    while True:
        phase = int(t / cfg.phase_s)
        burst = phase % 2 == 1
        rate = cfg.burst_rate_rps if burst else cfg.base_rate_rps
        # exponential gap at the *current* phase's rate: a phase boundary
        # mid-gap slightly blurs the edge, which is fine for a load model
        # (and keeps the draw count — hence determinism — simple)
        t += rng.expovariate(max(1e-9, rate))
        if t >= cfg.duration_s:
            break
        if cfg.hot_set_size > 1:
            # sibling-heavy mode: uniform over the hot set only — the
            # trace a co-resident engine serves without a single swap
            model = rng.randrange(cfg.hot_set_size)
        elif (
            burst and cfg.num_models > 1
            and rng.random() < cfg.burst_hot_frac
        ):
            # rotate the hot model per burst phase so every variant takes
            # a turn being the one the fleet must actuate toward
            model = (phase // 2) % cfg.num_models
        else:
            model = _pick(rng, base_w)
        plen = rng.randint(cfg.prompt_len_min, cfg.prompt_len_max)
        prompt = tuple(rng.randrange(1, cfg.vocab) for _ in range(plen))
        out.append(
            Arrival(
                t_s=round(t, 6),
                model=model,
                prompt=prompt,
                max_tokens=rng.randint(
                    cfg.max_tokens_min, cfg.max_tokens_max
                ),
            )
        )
    return out


def trace_digest(arrivals: Sequence[Arrival]) -> str:
    """sha256 fingerprint of a trace: what two same-seed runs must agree
    on byte-for-byte (CI's determinism gate and the bench result's
    ``arrival_trace_sha256``)."""
    h = hashlib.sha256()
    for a in arrivals:
        h.update(json.dumps(asdict(a), sort_keys=True).encode())
    return h.hexdigest()


def drain_time_s(cfg: FleetTrafficConfig) -> float:
    """Trace offset (seconds) where ``bench.py fleet --migrate`` drains
    its source instance: the middle of the FIRST burst phase (phases
    alternate base, burst, ... so the first burst spans
    ``[phase_s, 2*phase_s)``). Draining mid-burst is the adversarial
    moment — the source is at its deepest queue — and deriving it from
    the config (not a flag) keeps the leg reproducible per seed. Pure
    arithmetic on the config: the seeded arrival trace and its digest
    are untouched."""
    return 1.5 * cfg.phase_s


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) — no numpy dependency, and
    nearest-rank keeps p50 <= p95 <= p99 trivially monotonic."""
    if not values:
        return 0.0
    xs = sorted(values)
    rank = min(len(xs), max(1, math.ceil(q / 100.0 * len(xs))))
    return xs[rank - 1]
