"""Benchmark driver over the dual-pods control plane.

`ActuationBenchmark` wraps a simulated cluster (the package fakes with
injected latencies — reference mode "simulated",
benchmark_base.py:34-99) and exposes the operations scenarios compose:
deploy a pair, wait for readiness, scale down, and classify each actuation
as hot / warm / cold the way the controller's `fma_actuation_seconds`
path label does (controller.go:265-271).
"""

from __future__ import annotations

import asyncio
import statistics
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..api import constants as C
from ..testing import Harness, SimLatencies


@dataclass
class BenchmarkConfig:
    mode: str = "simulated"
    #: Simulated latencies, defaulted to the reference's published envelope:
    #: ~3 s wake for 64 GiB (README.md:16-26), tens-of-seconds engine cold
    #: start, scaled down 100x so scenario runs stay fast (scale factor is
    #: reported, timings multiply back up).
    time_scale: float = 0.01
    launcher_start_s: float = 20.0
    instance_create_s: float = 40.0
    wake_s: float = 3.0
    sleep_s: float = 2.0
    readiness_poll_s: float = 0.002

    def actuation_timeout_s(self) -> float:
        """Deadline scaled to the configured latencies: the worst (cold)
        path plus generous slack, never less than 30 s wall."""
        worst = (
            self.launcher_start_s + self.instance_create_s + self.wake_s
        ) * self.time_scale
        return max(30.0, worst * 3)

    def latencies(self) -> SimLatencies:
        s = self.time_scale
        return SimLatencies(
            launcher_start_s=self.launcher_start_s * s,
            instance_create_s=self.instance_create_s * s,
            wake_s=self.wake_s * s,
            sleep_s=self.sleep_s * s,
        )


@dataclass
class PairResult:
    name: str
    t_actuation_s: float
    path: str  # hot | warm | cold
    #: Scaled simulated-hardware latency injected during this actuation;
    #: the remainder of t_actuation_s is real (unscaled) harness/controller
    #: time and must NOT be multiplied back up by 1/time_scale.
    t_sim_s: float = 0.0


@dataclass
class ScenarioReport:
    scenario: str
    mode: str
    time_scale: float
    pairs: List[PairResult] = field(default_factory=list)
    extra: Dict[str, Any] = field(default_factory=dict)

    def summary(self) -> Dict[str, Any]:
        """The reference's metric vocabulary (benchmark.md:37-46).

        `T_actuation_s` is an UNSCALED ESTIMATE: only the simulated-hardware
        share of each measurement (`t_sim_s`, tracked by `SimLatencies`) is
        divided by time_scale; real harness/controller overhead is counted at
        face value instead of being amplified 1/time_scale x.
        `T_actuation_measured_s` is the raw wall time.
        """
        times = [p.t_actuation_s for p in self.pairs]
        if self.time_scale:
            unscaled = [
                p.t_sim_s / self.time_scale + (p.t_actuation_s - p.t_sim_s)
                for p in self.pairs
            ]
        else:
            unscaled = times
        by_path: Dict[str, int] = {}
        for p in self.pairs:
            by_path[p.path] = by_path.get(p.path, 0) + 1
        n = max(1, len(self.pairs))
        out = {
            "scenario": self.scenario,
            "mode": self.mode,
            "pairs": len(self.pairs),
            "T_actuation_s": {
                "min": min(unscaled, default=0.0),
                "max": max(unscaled, default=0.0),
                "avg": statistics.fmean(unscaled) if unscaled else 0.0,
                "median": statistics.median(unscaled) if unscaled else 0.0,
            },
            "T_actuation_measured_s": {
                "avg": statistics.fmean(times) if times else 0.0,
                "max": max(times, default=0.0),
            },
            "Hot_hit_rate": by_path.get("hot", 0) / n,
            "Warm_hit_rate": by_path.get("warm", 0) / n,
            "Cold_rate": by_path.get("cold", 0) / n,
            "paths": by_path,
        }
        out.update(self.extra)
        return out


class ActuationBenchmark:
    """One benchmark session over one simulated cluster."""

    def __init__(self, cfg: Optional[BenchmarkConfig] = None, **harness_kwargs) -> None:
        self.cfg = cfg or BenchmarkConfig()
        if self.cfg.mode != "simulated":
            raise ValueError(
                f"mode {self.cfg.mode!r}: ActuationBenchmark is the simulated "
                "driver; real-stack measurement is benchmark.live "
                "(LiveBenchmark / run_baseline_live)"
            )
        self.harness = Harness(latencies=self.cfg.latencies(), **harness_kwargs)
        self._counter = 0

    # -- cluster ops ---------------------------------------------------------

    def deploy_config(
        self, isc_name: str, lc_name: str = "bench-lc", port: int = 8000, options: str = ""
    ) -> None:
        h = self.harness
        if h.store.try_get("LauncherConfig", h.ns, lc_name) is None:
            h.add_lc(lc_name, max_instances=4)
        h.add_isc(isc_name, lc_name, port=port, options=options or f"--model {isc_name}")

    async def actuate(
        self,
        isc_name: str,
        node: str = "n1",
        chips: Optional[List[str]] = None,
        timeout_s: Optional[float] = None,
    ) -> PairResult:
        """Create a requester and wait until its readiness is relayed —
        T_actuation as the reference defines it (requester create -> Ready).
        Raises TimeoutError rather than hanging on a wedged reconcile; the
        default deadline scales with the configured sim latencies."""
        h = self.harness
        if timeout_s is None:
            timeout_s = self.cfg.actuation_timeout_s()
        self._counter += 1
        name = f"req-{isc_name}-{self._counter:06d}"
        t0 = time.monotonic()
        sim0 = self.harness.latencies.injected_total_s
        h.add_requester(name, isc_name, node=node, chips=chips or ["chip-0"])
        while not h.spis[name].ready:
            if time.monotonic() - t0 > timeout_s:
                raise TimeoutError(
                    f"{name} not ready after {timeout_s}s "
                    f"(status: {h.store.try_get('Pod', h.ns, name)})"
                )
            await asyncio.sleep(self.cfg.readiness_poll_s)
        elapsed = time.monotonic() - t0
        t_sim = self.harness.latencies.injected_total_s - sim0
        sd = self._server_data_for(name)
        return PairResult(
            name=name,
            t_actuation_s=elapsed,
            path=sd.path or "hot",
            t_sim_s=min(t_sim, elapsed),
        )

    async def scale_down(self, keep: int = 0) -> None:
        """Delete requesters, oldest-`keep` retained; instances go to sleep
        on their launchers. Creation order = the zero-padded actuation
        counter in the name (lexicographic name order breaks past 9)."""
        h = self.harness
        reqs = [
            p
            for p in h.store.list("Pod", h.ns)
            if C.INFERENCE_SERVER_CONFIG_ANNOTATION
            in (p["metadata"].get("annotations") or {})
        ]
        reqs.sort(key=lambda p: p["metadata"]["name"].rsplit("-", 1)[-1])
        for pod in reqs[keep:]:
            h.store.delete("Pod", h.ns, pod["metadata"]["name"])
        await h.settle()

    def _server_data_for(self, req_name: str):
        h = self.harness
        pod = h.store.get("Pod", h.ns, req_name)
        return h.controller.server_data[pod["metadata"]["uid"]]

    # -- lifecycle -----------------------------------------------------------

    async def __aenter__(self) -> "ActuationBenchmark":
        await self.harness.controller.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.harness.controller.stop()
