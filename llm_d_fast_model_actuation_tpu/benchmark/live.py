"""Live-mode benchmark: T_actuation against a REAL stack over real HTTP.

The reference's benchmark runs in three modes (benchmark_base.py:34-99):
simulated, kind (it creates the cluster), and remote (points at one). Here
"live" covers the last two: the benchmark speaks to an apiserver (the fake
one it can start itself, or any real one via --api-base), runs the real
dual-pods controller against it, and measures requester-create -> readiness
over the real launcher/engine subprocess stack.

Path classification is observed from the outside, the way an SRE would:
the launcher inventory before/after the actuation (instance created ->
cold), or the engine's /is_sleeping flip (asleep -> awake: warm), else hot.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import aiohttp

from ..api import constants as C
from .harness import PairResult, ScenarioReport


@dataclass
class LiveConfig:
    api_base: str  #: apiserver base URL (e.g. the fake apiserver, or kind)
    namespace: str = "bench"
    node: str = "n1"
    launcher_url: str = f"http://127.0.0.1:{C.LAUNCHER_SERVICE_PORT}"
    spi_port: int = 0  #: requester stub SPI (readiness relay target)
    probes_port: int = 0  #: requester stub probes (/ready polled)
    engine_port_base: int = 18100
    readiness_poll_s: float = 0.2
    timeout_s: float = 180.0
    #: engine options template; {port} is substituted per ISC
    engine_options: str = (
        "--model tiny --port {port} --num-pages 32 --max-batch 2 "
        "--page-size 8 --max-model-len 64"
    )
    engine_env: Dict[str, str] = field(
        default_factory=lambda: {"JAX_PLATFORMS": "cpu"}
    )


class LiveBenchmark:
    """Drives actuations against a running stack; the controller itself runs
    in-process against the same apiserver (what the deployment's controller
    pod would do)."""

    def __init__(self, cfg: LiveConfig) -> None:
        self.cfg = cfg
        self._isc_counter = 0  # engine-port assignment
        self._req_counter = 0  # requester pod naming
        self._session: Optional[aiohttp.ClientSession] = None
        self.ks = None
        self.ctl = None
        self.transports = None

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        from ..controller.clients import HttpTransports
        from ..controller.dualpods import DualPodsConfig, DualPodsController
        from ..controller.kubestore import KubeStore

        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=30)
        )
        self.ks = KubeStore(self.cfg.api_base, self.cfg.namespace, kinds=None)
        await self.ks.start()
        self.transports = HttpTransports()
        self.ctl = DualPodsController(
            self.ks, self.transports, DualPodsConfig(namespace=self.cfg.namespace)
        )
        await self.ctl.start()

    async def stop(self) -> None:
        if self.ctl:
            await self.ctl.stop()
        if self.transports:
            await self.transports.close()
        if self.ks:
            await self.ks.stop()
        if self._session:
            await self._session.close()

    # -- cluster objects -----------------------------------------------------

    def deploy_config(self, isc_name: str, lc_name: str = "bench-lc") -> int:
        """Create LC/ISC (+ the launcher Pod object mirroring the running
        launcher process); returns the ISC's engine port."""
        port = self.cfg.engine_port_base + self._isc_counter
        self._isc_counter += 1
        if self.ks.try_get("LauncherConfig", self.cfg.namespace, lc_name) is None:
            self.ks.create(
                {
                    "kind": "LauncherConfig",
                    "metadata": {"name": lc_name, "namespace": self.cfg.namespace},
                    "spec": {
                        "podTemplate": {
                            "metadata": {},
                            "spec": {"containers": [{"name": "launcher"}]},
                        },
                        "maxInstances": 4,
                    },
                }
            )
            self._create_launcher_pod_object(lc_name)
        self.ks.create(
            {
                "kind": "InferenceServerConfig",
                "metadata": {"name": isc_name, "namespace": self.cfg.namespace},
                "spec": {
                    "modelServerConfig": {
                        "port": port,
                        "options": self.cfg.engine_options.format(port=port),
                        "env_vars": dict(self.cfg.engine_env),
                    },
                    "launcherConfigName": lc_name,
                },
            }
        )
        return port

    def _create_launcher_pod_object(self, lc_name: str) -> None:
        from ..api.types import LauncherConfig
        from ..controller.populator import (
            build_launcher_template,
            specialize_to_node,
        )

        lc = LauncherConfig.from_dict(
            self.ks.get("LauncherConfig", self.cfg.namespace, lc_name)
        )
        _, ti_hash = build_launcher_template(lc)
        pod = specialize_to_node(lc, self.cfg.node, ti_hash)
        pod["metadata"]["namespace"] = self.cfg.namespace
        pod["metadata"]["name"] = "bench-launcher-live"
        pod["status"] = {
            "podIP": "127.0.0.1",
            "conditions": [{"type": "Ready", "status": "True"}],
        }
        self.ks.create(pod)

    # -- measurement ---------------------------------------------------------

    async def _http_json(self, method: str, url: str) -> Any:
        async with self._session.request(method, url) as resp:
            resp.raise_for_status()
            return await resp.json()

    async def _instances(self) -> Dict[str, Any]:
        return await self._http_json(
            "GET", self.cfg.launcher_url + "/v2/vllm/instances"
        )

    async def _stub_ready(self) -> bool:
        try:
            async with self._session.get(
                f"http://127.0.0.1:{self.cfg.probes_port}/ready"
            ) as resp:
                return resp.status == 200
        except aiohttp.ClientError:
            return False

    async def _reset_stub(self) -> None:
        async with self._session.post(
            f"http://127.0.0.1:{self.cfg.spi_port}/v1/become-unready"
        ) as resp:
            resp.raise_for_status()

    async def actuate(self, isc_name: str, engine_port: int) -> PairResult:
        """Create a requester Pod; T_actuation = create -> readiness relay
        observed at the stub's probes endpoint (the reference's definition:
        requester create -> Ready)."""
        await self._reset_stub()
        before = await self._instances()
        before_ids = {s["instance_id"] for s in before.get("instances", [])}
        was_sleeping = False
        try:
            body = await self._http_json(
                "GET", f"http://127.0.0.1:{engine_port}/is_sleeping"
            )
            was_sleeping = bool(body.get("is_sleeping"))
        except aiohttp.ClientError:
            pass

        name = f"bench-req-{self._req_counter:06d}"
        self._req_counter += 1
        t0 = time.monotonic()
        self.ks.create(
            {
                "kind": "Pod",
                "metadata": {
                    "name": name,
                    "namespace": self.cfg.namespace,
                    "annotations": {
                        C.INFERENCE_SERVER_CONFIG_ANNOTATION: isc_name,
                        C.ADMIN_PORT_ANNOTATION: str(self.cfg.spi_port),
                    },
                },
                "spec": {
                    "nodeName": self.cfg.node,
                    "containers": [
                        {"name": C.INFERENCE_SERVER_CONTAINER_NAME}
                    ],
                },
                "status": {"podIP": "127.0.0.1"},
            }
        )
        deadline = t0 + self.cfg.timeout_s
        while not await self._stub_ready():
            if time.monotonic() > deadline:
                raise TimeoutError(f"{name} not ready in {self.cfg.timeout_s}s")
            await asyncio.sleep(self.cfg.readiness_poll_s)
        elapsed = time.monotonic() - t0

        after = await self._instances()
        after_ids = {s["instance_id"] for s in after.get("instances", [])}
        if after_ids - before_ids:
            path = "cold"
        elif was_sleeping:
            path = "warm"
        else:
            path = "hot"
        return PairResult(name=name, t_actuation_s=elapsed, path=path)

    async def scale_down(self, isc_name: str, engine_port: int) -> None:
        """Delete this ISC's requesters; wait until the engine reports
        sleeping (the instance survives for the next warm hit)."""
        for pod in self.ks.list("Pod", self.cfg.namespace):
            ann = pod["metadata"].get("annotations") or {}
            if ann.get(C.INFERENCE_SERVER_CONFIG_ANNOTATION) == isc_name:
                self.ks.delete("Pod", self.cfg.namespace, pod["metadata"]["name"])
        deadline = time.monotonic() + self.cfg.timeout_s
        while time.monotonic() < deadline:
            try:
                body = await self._http_json(
                    "GET", f"http://127.0.0.1:{engine_port}/is_sleeping"
                )
                if body.get("is_sleeping"):
                    return
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(self.cfg.readiness_poll_s)
        raise TimeoutError("instance never went to sleep after scale-down")


async def run_baseline_live(cfg: LiveConfig) -> ScenarioReport:
    """cold -> scale-down -> warm, measured over the live stack (the
    reference baseline scenario shape)."""
    bench = LiveBenchmark(cfg)
    await bench.start()
    report = ScenarioReport("baseline", "live", time_scale=0.0)
    try:
        port = bench.deploy_config("bench-isc")
        report.pairs.append(await bench.actuate("bench-isc", port))
        await bench.scale_down("bench-isc", port)
        report.pairs.append(await bench.actuate("bench-isc", port))
        report.extra["paths_in_order"] = [p.path for p in report.pairs]
    finally:
        await bench.stop()
    return report
