"""Admission rules: the deploy/policies CEL, executable in Python.

Two uses: (1) unit-testable source of truth for what the cluster policies
enforce (deploy/policies/*.yaml mirror these semantics — reference
`fma-immutable-fields` and `fma-bound-serverreqpod`,
config/validating-admission-policies/fma-immutable-fields.yaml:1-33);
(2) structural validation of the three CRD kinds for clients and tests
without an apiserver.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from .api import constants as C
from .controller.directpath import LAST_USED_ANNOTATION, NOMINAL_HASH_ANNOTATION
from .parallel.topology import SliceTopology

#: Service accounts allowed to touch FMA-managed metadata
#: (fma-immutable-fields.yaml's serviceAccountMatch).
FMA_CONTROLLER_SA = re.compile(
    r"^system:serviceaccount:[^:]+:[^:]*-fma-controllers$"
)

#: Pod metadata the controllers own (frozen for everyone else).
PROTECTED_ANNOTATIONS = (
    C.REQUESTER_ANNOTATION,
    C.INSTANCE_ID_ANNOTATION,
    C.SERVER_PORT_ANNOTATION,
    C.ENGINE_CONFIG_ANNOTATION,
    C.ISC_ROUTING_METADATA_ANNOTATION,
    C.ACCELERATORS_ANNOTATION,
    C.STATUS_ANNOTATION,
    NOMINAL_HASH_ANNOTATION,
    LAST_USED_ANNOTATION,
)
PROTECTED_LABELS = (C.DUAL_LABEL, C.INSTANCE_LABEL, C.SLEEPING_LABEL)

#: Annotations frozen on a BOUND requester (they define the committed
#: actuation; editing them mid-binding desyncs the provider).
BOUND_ACTUATION_ANNOTATIONS = (
    C.SERVER_PATCH_ANNOTATION,
    C.INFERENCE_SERVER_CONFIG_ANNOTATION,
    C.ADMIN_PORT_ANNOTATION,
)


def is_fma_controller(username: str) -> bool:
    return bool(FMA_CONTROLLER_SA.match(username))


def _get(obj: Dict[str, Any], section: str, key: str) -> str:
    return ((obj.get("metadata") or {}).get(section) or {}).get(key, "")


def validate_pod_update(
    old: Dict[str, Any], new: Dict[str, Any], username: str
) -> List[str]:
    """The two Pod policies; returns denial messages (empty = admitted)."""
    if is_fma_controller(username):
        return []
    errors: List[str] = []
    for key in PROTECTED_ANNOTATIONS:
        if _get(old, "annotations", key) != _get(new, "annotations", key):
            errors.append(
                f"annotation {key} is FMA-managed and may only be changed "
                "by the FMA controllers"
            )
    for key in PROTECTED_LABELS:
        if _get(old, "labels", key) != _get(new, "labels", key):
            errors.append(
                f"label {key} is FMA-managed and may only be changed "
                "by the FMA controllers"
            )
    # bound requester: actuation annotations frozen
    is_requester = _get(old, "annotations", C.SERVER_PATCH_ANNOTATION) or _get(
        old, "annotations", C.INFERENCE_SERVER_CONFIG_ANNOTATION
    )
    if is_requester and _get(old, "labels", C.DUAL_LABEL):
        for key in BOUND_ACTUATION_ANNOTATIONS:
            if _get(old, "annotations", key) != _get(new, "annotations", key):
                errors.append(
                    f"annotation {key} is frozen while the requester is bound"
                )
    return errors


# --------------------------------------------------------- CRD validation


def validate_isc(obj: Dict[str, Any]) -> List[str]:
    errors: List[str] = []
    spec = obj.get("spec") or {}
    msc = spec.get("modelServerConfig")
    if not isinstance(msc, dict):
        return ["spec.modelServerConfig is required"]
    port = msc.get("port")
    if not isinstance(port, int) or not (1 <= port <= 65535):
        errors.append("spec.modelServerConfig.port must be an integer in 1..65535")
    acc = msc.get("accelerator") or {}
    chips = acc.get("chips", 1)
    if not isinstance(chips, int) or chips < 1:
        errors.append("spec.modelServerConfig.accelerator.chips must be >= 1")
    hosts = acc.get("hosts", 1)
    if not isinstance(hosts, int) or hosts < 1:
        errors.append("spec.modelServerConfig.accelerator.hosts must be >= 1")
        hosts = 1
    if hosts > 1 and not acc.get("topology"):
        errors.append(
            "accelerator.hosts > 1 requires accelerator.topology (the "
            "global slice shape)"
        )
    topo = acc.get("topology", "")
    if topo:
        try:
            parsed = SliceTopology.parse(topo)
            # chips is per host; the topology is global (chips x hosts)
            want = chips * hosts if isinstance(chips, int) and chips >= 1 else None
            if want is not None and parsed.num_chips != want:
                errors.append(
                    f"accelerator.topology {topo} has {parsed.num_chips} "
                    f"chips but accelerator.chips x hosts is {want}"
                )
        except ValueError as e:
            errors.append(f"accelerator.topology: {e}")
    for section in ("labels", "annotations", "env_vars"):
        val = msc.get(section)
        if val is not None and not (
            isinstance(val, dict)
            and all(isinstance(k, str) and isinstance(v, str) for k, v in val.items())
        ):
            errors.append(f"spec.modelServerConfig.{section} must map string->string")
    return errors


def validate_lc(obj: Dict[str, Any]) -> List[str]:
    errors: List[str] = []
    spec = obj.get("spec") or {}
    if not isinstance(spec.get("podTemplate"), dict):
        errors.append("spec.podTemplate is required")
    max_instances = spec.get("maxInstances", 1)
    if not isinstance(max_instances, int) or max_instances < 1:
        errors.append("spec.maxInstances must be >= 1")
    return errors


def validate_lpp(obj: Dict[str, Any]) -> List[str]:
    errors: List[str] = []
    spec = obj.get("spec") or {}
    if not isinstance(spec.get("nodeSelector"), dict):
        errors.append("spec.nodeSelector is required")
    cfl = spec.get("countForLauncher")
    if not isinstance(cfl, list) or not cfl:
        errors.append("spec.countForLauncher must be a non-empty list")
        return errors
    for i, entry in enumerate(cfl):
        if not isinstance(entry, dict):
            errors.append(f"spec.countForLauncher[{i}] must be an object")
            continue
        if not entry.get("launcherConfigName"):
            errors.append(f"spec.countForLauncher[{i}].launcherConfigName is required")
        count = entry.get("launcherCount")
        if not isinstance(count, int) or count < 0:
            errors.append(f"spec.countForLauncher[{i}].launcherCount must be >= 0")
    ranges = ((spec.get("nodeSelector") or {}).get("allocatableResources")) or {}
    for res, rng in ranges.items():
        lo, hi = (rng or {}).get("min"), (rng or {}).get("max")
        try:
            from .api.types import parse_quantity

            lo_v = parse_quantity(lo) if lo is not None else None
            hi_v = parse_quantity(hi) if hi is not None else None
            if lo_v is not None and hi_v is not None and lo_v > hi_v:
                errors.append(f"allocatableResources[{res}]: min > max")
        except (ValueError, TypeError):
            errors.append(f"allocatableResources[{res}]: unparsable quantity")
    return errors


_VALIDATORS = {
    "InferenceServerConfig": validate_isc,
    "LauncherConfig": validate_lc,
    "LauncherPopulationPolicy": validate_lpp,
}


def validate(obj: Dict[str, Any]) -> List[str]:
    """Dispatch on kind; unknown kinds are admitted (no opinion)."""
    fn = _VALIDATORS.get(obj.get("kind", ""))
    return fn(obj) if fn else []


def review(request: Dict[str, Any]) -> Dict[str, Any]:
    """AdmissionReview-shaped entry point (for a webhook deployment):
    request = {object, oldObject?, userInfo: {username}, operation}."""
    op = request.get("operation", "CREATE")
    obj = request.get("object") or {}
    errors: List[str] = []
    if obj.get("kind") == "Pod" and op == "UPDATE":
        errors = validate_pod_update(
            request.get("oldObject") or {},
            obj,
            ((request.get("userInfo") or {}).get("username", "")),
        )
    else:
        errors = validate(obj)
    return {
        "allowed": not errors,
        **({"status": {"message": "; ".join(errors)}} if errors else {}),
    }
