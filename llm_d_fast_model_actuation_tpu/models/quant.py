"""Weight-only int8 quantization (W8A16) for the serving path.

Decode on TPU is HBM-bandwidth-bound: every step reads every weight byte
(SURVEY.md §6; the reference's engine, vLLM, ships the same technique for
the same reason). Symmetric per-output-channel int8 halves the weight
bytes — near-2x on the decode roofline — while activations stay bf16 and
matmuls run on the MXU: XLA fuses the int8->bf16 upconvert into the
matmul's operand read, so HBM traffic is the int8 bytes.

Representation: a quantized weight is the dict ``{"q": int8[..., out],
"s": f32[out-broadcastable]}`` with ``W ≈ q * s``. Since the scale is
per OUTPUT channel, ``x @ W == (x @ q) * s`` — the matmul result is
rescaled, not the weight, so no dequantized copy ever materializes.

Quantized and plain weights coexist: every matmul in the model forward
goes through `qmat`, which dispatches on the leaf shape. Norms and the
embedding table stay bf16 (the embedding is a gather, not a matmul; its
tied-head use stays bf16 too).
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

#: weight stacks quantized in a llama-family layer pytree + top level
LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def qmat(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """``x @ w`` for a plain or quantized weight."""
    if is_quantized(w):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def quantize_weight(w: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Symmetric per-output-channel int8: scale over all axes but the last.

    Handles both single (in, out) and layer-stacked (L, in, out) weights —
    the scale keeps a broadcastable shape so `lax.scan` slicing a layer
    slices the scale with it.
    """
    # reduce ONLY the fan-in axis: leading stack axes (the scan's layer
    # axis) keep their own scales, so slicing a layer slices its scale
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=w.ndim - 2, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return {"q": q, "s": scale.astype(jnp.float32)}


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize a llama-family param pytree in place of the bf16 stacks.
    Embedding and norms stay bf16. MoE trees reuse the dense names for
    their 4-D expert stacks ([L, E, in, out]; moe_ffn's _qeinsum consumes
    the quantized form); the router stays bf16 (its output feeds a
    softmax — precision matters and it is tiny)."""
    out = dict(params)
    layers = dict(params["layers"])
    for name in LAYER_WEIGHTS:
        w = layers.get(name)
        if w is not None and not is_quantized(w) and w.ndim in (3, 4):
            layers[name] = quantize_weight(w)
    out["layers"] = layers
    head = params.get("lm_head")
    if head is not None and not is_quantized(head):
        out["lm_head"] = quantize_weight(head)
    return out


def quantized_axes(axes: Dict[str, Any]) -> Dict[str, Any]:
    """Logical-axis pytree matching `quantize_params`' structure: q keeps
    the original weight's axes; the broadcast scale shards only its output
    axis (other dims are size-1)."""
    out = dict(axes)
    layers = dict(axes["layers"])
    for name in LAYER_WEIGHTS:
        ax = layers.get(name)
        if ax is not None and len(ax) in (3, 4):
            # scale keeps every axis except fan-in (size-1 there):
            # (L, 1, out) for dense stacks, (L, E, 1, out) for experts
            layers[name] = {
                "q": ax,
                "s": ax[:-2] + (None, ax[-1]),
            }
    out["layers"] = layers
    if "lm_head" in axes:
        ax = axes["lm_head"]
        out["lm_head"] = {"q": ax, "s": (None, ax[-1])}
    return out





