"""Weight-only int8 quantization (W8A16) for the serving path.

Decode on TPU is HBM-bandwidth-bound: every step reads every weight byte
(SURVEY.md §6; the reference's engine, vLLM, ships the same technique for
the same reason). Symmetric per-output-channel int8 halves the weight
bytes — near-2x on the decode roofline — while activations stay bf16 and
matmuls run on the MXU: XLA fuses the int8->bf16 upconvert into the
matmul's operand read, so HBM traffic is the int8 bytes.

Representation: a quantized weight is the dict ``{"q": int8[..., out],
"s": f32[out-broadcastable]}`` with ``W ≈ q * s``. Since the scale is
per OUTPUT channel, ``x @ W == (x @ q) * s`` — the matmul result is
rescaled, not the weight, so no dequantized copy ever materializes.

Quantized and plain weights coexist: every matmul in the model forward
goes through `qmat`, which dispatches on the leaf shape. Norms and the
embedding table stay bf16 (the embedding is a gather, not a matmul; its
tied-head use stays bf16 too).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

#: weight stacks quantized in a llama-family layer pytree + top level
LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def qmat(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """``x @ w`` for a plain or quantized weight."""
    if is_quantized(w):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def quantize_weight(w: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Symmetric per-output-channel int8: scale over all axes but the last.

    Handles both single (in, out) and layer-stacked (L, in, out) weights —
    the scale keeps a broadcastable shape so `lax.scan` slicing a layer
    slices the scale with it.
    """
    # reduce ONLY the fan-in axis: leading stack axes (the scan's layer
    # axis) keep their own scales, so slicing a layer slices its scale
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=w.ndim - 2, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return {"q": q, "s": scale.astype(jnp.float32)}


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize a llama-family param pytree in place of the bf16 stacks.
    Embedding and norms stay bf16. MoE trees reuse the dense names for
    their 4-D expert stacks ([L, E, in, out]; moe_ffn's _qeinsum consumes
    the quantized form); the router stays bf16 (its output feeds a
    softmax — precision matters and it is tiny)."""
    out = dict(params)
    layers = dict(params["layers"])
    for name in LAYER_WEIGHTS:
        w = layers.get(name)
        if w is not None and not is_quantized(w) and w.ndim in (3, 4):
            layers[name] = quantize_weight(w)
    out["layers"] = layers
    head = params.get("lm_head")
    if head is not None and not is_quantized(head):
        out["lm_head"] = quantize_weight(head)
    return out


# -- compressed actuation transfers (docs/perf.md "Compressed actuation") ----
#
# The serving-path W8A16 above changes what the MODEL computes; the
# transfer quantization below changes only how weight bytes CROSS the
# PCIe/host boundary on sleep/wake/swap (engine/sleep.py). A leaf is
# quantized right before it leaves HBM (or host-side when staging a
# full-precision pool entry), the half-size payload moves, and the wake
# dequantizes on device — the engine always serves plain cfg.dtype arrays,
# so no program recompiles and `qmat` never sees these.
#
# Numerics contract: opt-in and lossy-ONCE. The first quantized offload
# rounds each eligible weight to its int8/fp8 representation; every later
# cycle reproduces the exact same post-quantization bits, because (a) the
# int8 scale is cached by the sleeper and reused (re-quantizing
# dequant(q, s) with the same s recovers q exactly: |q|<=127 and the
# bf16/f32 round-trip error is < 0.25 of a quantization step) and (b) the
# fp8 path is a plain dtype round trip, exact by construction.

#: transfer quantization modes (--sleep-quant)
TRANSFER_MODES = ("off", "int8", "fp8")

#: top-level leaves the default "hot head" keeps at full precision
HOT_HEAD_KEYS = ("embed", "lm_head")


def fp8_dtype():
    """The fp8 transfer dtype (e4m3: weight-shaped range, 3 mantissa
    bits). Raises ImportError where ml_dtypes lacks it."""
    import ml_dtypes

    return ml_dtypes.float8_e4m3fn


def transfer_quant_supported(mode: str) -> Optional[str]:
    """None when `mode` can run here, else a human reason (the flag
    validation surface)."""
    if mode in ("", "off"):
        return None
    if mode not in TRANSFER_MODES:
        return f"unknown sleep-quant mode {mode!r} (want {TRANSFER_MODES})"
    if mode == "fp8":
        try:
            fp8_dtype()
        except Exception as e:  # noqa: BLE001 — report, caller rejects
            return f"fp8 transfers need ml_dtypes float8_e4m3fn: {e}"
    return None


@dataclass
class TransferQuant:
    """Per-leaf metadata for a transfer-quantized payload: what the wake
    needs to rebuild the full-precision array on device. Rides NEXT TO the
    host state tree (an aligned flat list), never inside it — the tree
    keeps its structure so digest alignment and sharding trees stay valid."""

    mode: str  #: "int8" | "fp8"
    orig_dtype: str  #: numpy dtype string of the full-precision leaf
    #: float32 per-output-channel scale, broadcastable (int8 only)
    scale: Optional[np.ndarray] = None
    #: shard view of the leaf this payload was quantized FROM (the
    #: ``str(PartitionSpec)`` of a mesh-sharded device leaf; None for
    #: single-device / host-staged payloads): quantize/dequantize run
    #: shard-locally on device — the per-output-channel scale reduction
    #: is over the fan-in axis, which XLA computes shard-local where
    #: that axis is unsharded and via one exact all-reduce max where it
    #: is ('tp'-sharded ``w_down``) — and the restore path cross-checks
    #: this spec against its placement target so a payload can never be
    #: silently expanded under a different sharding than it came from
    spec: Optional[str] = None

    @property
    def scale_nbytes(self) -> int:
        return int(self.scale.nbytes) if self.scale is not None else 0


def _is_float_dtype(dt: Any) -> bool:
    try:
        return jnp.issubdtype(np.dtype(dt), jnp.floating)
    except TypeError:
        return False


def transfer_quant_plan(
    state: Any, hot_head: bool = True, prefix: str = "params"
) -> List[bool]:
    """Which leaves of ``state`` a quantized transfer compresses, aligned
    with ``jax.tree.flatten(state)`` order (the same alignment contract as
    chunk_store.aligned_digests).

    Eligible: floating-point weight stacks under the ``prefix`` subtree —
    the layer matmul weights (LAYER_WEIGHTS, ndim 3/4), plus ``embed`` and
    ``lm_head`` (ndim 2) when ``hot_head`` is False. Norms, biases, the
    KV pool, and scheduler arrays never quantize; with the default hot
    head on, embeddings / final norm / lm_head stay full precision."""
    from jax.tree_util import tree_flatten_with_path

    flat, _ = tree_flatten_with_path(state)
    out: List[bool] = []
    for path, leaf in flat:
        keys = []
        for k in path:
            if hasattr(k, "key"):
                keys.append(str(k.key))
            elif hasattr(k, "idx"):
                keys.append(str(k.idx))
            else:  # pragma: no cover — exotic pytree key types
                keys.append(str(k))
        if prefix:
            if not keys or keys[0] != prefix:
                out.append(False)
                continue
            keys = keys[1:]
        ndim = len(getattr(leaf, "shape", ()))
        dt = getattr(leaf, "dtype", None)
        if not keys or ndim < 2 or dt is None or not _is_float_dtype(dt):
            out.append(False)
        elif keys[0] == "layers" and keys[-1] in LAYER_WEIGHTS and ndim in (3, 4):
            out.append(True)
        elif not hot_head and keys[-1] in HOT_HEAD_KEYS and ndim == 2:
            out.append(True)
        else:
            out.append(False)
    return out


def payload_nbytes(shape: Tuple[int, ...], mode: str) -> int:
    """Wire bytes of one quantized leaf: 1-byte payload + the int8 path's
    f32 scale (axis ndim-2 reduced to 1). Shapes only — the swap's bucket
    partitioner and the prefetch admission estimate both size transfers
    without materializing anything."""
    elems = 1
    for d in shape:
        elems *= int(d)
    scale = 0
    if mode == "int8":
        scale = (elems // max(1, int(shape[-2]))) * 4
    return elems + scale


def _shard_spec_str(arr: Any) -> Optional[str]:
    """``str(PartitionSpec)`` of a mesh-sharded device array (the shard
    view recorded in :class:`TransferQuant`); None for single-device and
    host arrays."""
    sh = getattr(arr, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is None or getattr(sh, "num_devices", 1) <= 1:
        return None
    return str(spec)


def quantize_leaf(
    arr: Any, mode: str, scale: Optional[Any] = None
) -> Tuple[Any, TransferQuant]:
    """Quantize one leaf for transfer with jnp ops — ON DEVICE when `arr`
    is a device array, so only the payload crosses the boundary. A
    mesh-sharded leaf quantizes SHARD-LOCALLY (elementwise ops keep the
    input's sharding; the amax reduction is shard-local except over a
    'tp'-sharded fan-in axis, where XLA inserts one exact all-reduce
    max), and the leaf's shard view is recorded in the metadata.

    ``scale`` (the sleeper's cached scale from this leaf's first
    quantization) makes re-quantization bit-idempotent: round(w'/s) with
    w' = dequant(q, s) recovers exactly q. Returns (payload, meta); the
    meta's scale is normalized to host numpy."""
    orig = str(np.dtype(arr.dtype))
    spec = _shard_spec_str(arr)
    if mode == "fp8":
        return jnp.asarray(arr).astype(fp8_dtype()), TransferQuant(
            mode="fp8", orig_dtype=orig, spec=spec
        )
    w = jnp.asarray(arr).astype(jnp.float32)
    if scale is None:
        amax = jnp.max(jnp.abs(w), axis=w.ndim - 2, keepdims=True)
        s = jnp.maximum(amax / 127.0, 1e-8)
    else:
        s = jnp.asarray(scale, dtype=jnp.float32)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return q, TransferQuant(
        mode="int8",
        orig_dtype=orig,
        scale=np.asarray(s, dtype=np.float32),
        spec=spec,
    )


def quantize_leaf_np(
    arr: np.ndarray, mode: str, scale: Optional[np.ndarray] = None
) -> Tuple[np.ndarray, TransferQuant]:
    """Host-side twin of :func:`quantize_leaf` (pure numpy): the staging
    path for full-precision pool entries and prefetched weights, where no
    device round trip is wanted. Same rounding (half-to-even), so both
    paths produce identical payloads for identical input bits."""
    orig = str(np.dtype(arr.dtype))
    if mode == "fp8":
        return np.asarray(arr).astype(fp8_dtype()), TransferQuant(
            mode="fp8", orig_dtype=orig
        )
    w = np.asarray(arr).astype(np.float32)
    if scale is None:
        amax = np.max(np.abs(w), axis=w.ndim - 2, keepdims=True)
        s = np.maximum(amax / 127.0, np.float32(1e-8)).astype(np.float32)
    else:
        s = np.asarray(scale, dtype=np.float32)
    q = np.clip(np.rint(w / s), -127, 127).astype(np.int8)
    return q, TransferQuant(mode="int8", orig_dtype=orig, scale=s)


def dequantize_leaf(payload: Any, meta: TransferQuant) -> Any:
    """Rebuild the full-precision array from a payload with jnp ops — ON
    DEVICE when the payload is a device array (the wake-side dequant that
    rides under the remaining H2D stream)."""
    dt = np.dtype(meta.orig_dtype)
    if meta.mode == "fp8":
        return jnp.asarray(payload).astype(dt)
    w = jnp.asarray(payload).astype(jnp.float32) * jnp.asarray(meta.scale)
    return w.astype(dt)


def dequantize_leaf_np(payload: np.ndarray, meta: TransferQuant) -> np.ndarray:
    """Host-side twin of :func:`dequantize_leaf`."""
    dt = np.dtype(meta.orig_dtype)
    if meta.mode == "fp8":
        return np.asarray(payload).astype(dt)
    w = np.asarray(payload).astype(np.float32) * meta.scale
    return w.astype(dt)


def transfer_digest(payload: Any, meta: TransferQuant) -> str:
    """Content digest of a quantized chunk (payload + scale + mode + the
    dtype it dequantizes to): what the tiered pool dedupes quantized
    entries on. A distinct digest space from the full-precision leaf
    digests — a quantized payload must never content-match (and be handed
    out as) the full-precision tensor it came from. Because the preimage
    includes leaf_digest(payload), equal "q:" digests imply bit-equal
    payloads, which is what lets the disk spill tier content-verify a
    reloaded quant chunk against the ``content`` field its spill header
    recorded (chunk_store._load_spilled)."""
    from ..engine.chunk_store import QUANT_DIGEST_PREFIX, leaf_digest

    h = hashlib.sha256()
    h.update(f"tq|{meta.mode}|{meta.orig_dtype}|".encode())
    h.update(leaf_digest(np.asarray(payload)).encode())
    if meta.scale is not None:
        h.update(leaf_digest(np.asarray(meta.scale)).encode())
    return QUANT_DIGEST_PREFIX + h.hexdigest()


def transfer_digest_map(
    state: Any, metas: list, prefix: str = "params"
) -> Dict[str, str]:
    """Flat weight key -> :func:`transfer_digest` for the quantized leaves
    of a slept/staged tree (``metas`` aligned with its flatten order).
    These live in a digest space disjoint from the full-precision leaf
    digests, so the tiered pool dedupes quantized siblings against each
    other and NEVER against the fp tensors they approximate."""
    from jax.tree_util import tree_flatten_with_path

    flat, _ = tree_flatten_with_path(state)
    out: Dict[str, str] = {}
    for (path, leaf), meta in zip(flat, metas):
        if meta is None:
            continue
        keys = []
        for k in path:
            if hasattr(k, "key"):
                keys.append(str(k.key))
            elif hasattr(k, "idx"):
                keys.append(str(k.idx))
            else:  # pragma: no cover — exotic pytree key types
                keys.append(str(k))
        if prefix:
            if not keys or keys[0] != prefix:
                continue
            keys = keys[1:]
        out["/".join(keys)] = transfer_digest(leaf, meta)
    return out


def quantized_axes(axes: Dict[str, Any]) -> Dict[str, Any]:
    """Logical-axis pytree matching `quantize_params`' structure: q keeps
    the original weight's axes; the broadcast scale shards only its output
    axis (other dims are size-1)."""
    out = dict(axes)
    layers = dict(axes["layers"])
    for name in LAYER_WEIGHTS:
        ax = layers.get(name)
        if ax is not None and len(ax) in (3, 4):
            # scale keeps every axis except fan-in (size-1 there):
            # (L, 1, out) for dense stacks, (L, E, 1, out) for experts
            layers[name] = {
                "q": ax,
                "s": ax[:-2] + (None, ax[-1]),
            }
    out["layers"] = layers
    if "lm_head" in axes:
        ax = axes["lm_head"]
        out["lm_head"] = {"q": ax, "s": (None, ax[-1])}
    return out





