"""Model-family dispatch: init/sharding by config type.

The forward path (prefill/decode_step in models/llama.py) is shared across
families — the scanned layer body dispatches its FFN on the config
(`llama._ffn`), so the engine never branches. Only initialization and the
logical-axes pytree differ per family.
"""

from __future__ import annotations

from typing import Any, Dict

import jax

from . import llama, moe


def init_params_for(key: jax.Array, cfg: llama.LlamaConfig) -> Dict[str, Any]:
    if isinstance(cfg, moe.MoeConfig):
        params = moe.init_params(key, cfg)
    else:
        params = llama.init_params(key, cfg)
    return maybe_quantize(cfg, params)


def logical_axes_for(cfg: llama.LlamaConfig) -> Dict[str, Any]:
    if isinstance(cfg, moe.MoeConfig):
        axes = moe.param_logical_axes(cfg)
    else:
        axes = llama.param_logical_axes(cfg)
    if getattr(cfg, "quantization", "") == "int8":
        from .quant import quantized_axes

        axes = quantized_axes(axes)
    return axes


def maybe_quantize(cfg: llama.LlamaConfig, params: Dict[str, Any]) -> Dict[str, Any]:
    """Apply the config's weight quantization (runtime quantization: bf16
    checkpoints stay bf16 on disk; HBM holds the int8 form)."""
    q = getattr(cfg, "quantization", "")
    if not q:
        return params
    if q != "int8":
        raise ValueError(f"unknown quantization {q!r}")
    from .quant import quantize_params

    return quantize_params(params)
