"""Model-family dispatch: init/sharding by config type.

The forward path (prefill/decode_step in models/llama.py) is shared across
families — the scanned layer body dispatches its FFN on the config
(`llama._ffn`), so the engine never branches. Only initialization and the
logical-axes pytree differ per family.
"""

from __future__ import annotations

from typing import Any, Dict

import jax

from . import llama, moe


def init_params_for(key: jax.Array, cfg: llama.LlamaConfig) -> Dict[str, Any]:
    if isinstance(cfg, moe.MoeConfig):
        return moe.init_params(key, cfg)
    return llama.init_params(key, cfg)


def logical_axes_for(cfg: llama.LlamaConfig) -> Dict[str, Any]:
    if isinstance(cfg, moe.MoeConfig):
        return moe.param_logical_axes(cfg)
    return llama.param_logical_axes(cfg)
