"""Mixtral-style sparse-MoE decoder — the second model family.

Same attention trunk as the Llama family (models/llama.py — one scanned
layer body, paged KV, GQA); the FFN is a top-k router over E experts.

TPU/SPMD design:
  * expert weights are stacked ``[layers, experts, ...]`` and the experts
    axis carries the ``expert -> ep`` logical sharding rule
    (parallel/mesh.py LOGICAL_RULES): each ep shard holds E/ep experts;
  * dispatch is DENSE-compute, sparse-weight: every expert runs on every
    token and the router's (renormalized) top-k probabilities weight the
    sum. Under ep sharding each device computes only its local experts and
    the weighted sum's contraction over E becomes one psum over ep — no
    scatter/gather, no capacity factors, no dynamic shapes, which is
    exactly what XLA wants. The FLOPs cost vs token-dropping dispatch is
    E/k per device group, paid deliberately for static shapes (the
    standard small-scale JAX MoE trade; swap in a ragged Pallas dispatch
    when expert counts grow past the arithmetic-intensity break-even).

Reference parity: the reference serves MoE through vLLM's Mixtral support
(SURVEY §2.9 model families); this is the TPU-native equivalent.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from . import llama


@dataclass(frozen=True)
class MoeConfig(llama.LlamaConfig):
    num_experts: int = 8
    experts_per_token: int = 2

    @classmethod
    def mixtral_8x7b(cls) -> "MoeConfig":
        return cls(
            vocab_size=32000,
            hidden_size=4096,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            intermediate_size=14336,
            rope_theta=1e6,
            max_seq_len=32768,
            num_experts=8,
            experts_per_token=2,
        )

    @classmethod
    def tiny_moe(cls, vocab: int = 256) -> "MoeConfig":
        return cls(
            vocab_size=vocab,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            intermediate_size=96,
            rope_theta=10000.0,
            max_seq_len=128,
            num_experts=4,
            experts_per_token=2,
        )

    def num_params(self) -> int:
        h, f, e = self.hidden_size, self.intermediate_size, self.num_experts
        per_layer = (
            2 * h  # norms
            + h * self.q_dim
            + 2 * h * self.kv_dim
            + self.q_dim * h
            + h * e  # router
            + e * 3 * h * f  # experts
        )
        head = 0 if self.tie_embeddings else h * self.vocab_size
        return (
            self.vocab_size * h + self.num_layers * per_layer + h + head
        )


def init_params(key: jax.Array, cfg: MoeConfig) -> Dict[str, Any]:
    """Random-init params in the Llama layout, with per-layer expert stacks
    (``[L, E, ...]``) and a router replacing the dense FFN."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    h, L, E, f = (
        cfg.hidden_size,
        cfg.num_layers,
        cfg.num_experts,
        cfg.intermediate_size,
    )

    def norm_init(shape):
        return jnp.ones(shape, dtype=cfg.dtype)

    def dense_init(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, dtype=jnp.float32) * fan_in**-0.5
        ).astype(cfg.dtype)

    ks = jax.random.split(k_layers, 8)
    layers = {
        "attn_norm": norm_init((L, h)),
        "wq": dense_init(ks[0], (L, h, cfg.q_dim), h),
        "wk": dense_init(ks[1], (L, h, cfg.kv_dim), h),
        "wv": dense_init(ks[2], (L, h, cfg.kv_dim), h),
        "wo": dense_init(ks[3], (L, cfg.q_dim, h), cfg.q_dim),
        "mlp_norm": norm_init((L, h)),
        "router": dense_init(ks[4], (L, h, E), h),
        "w_gate": dense_init(ks[5], (L, E, h, f), h),
        "w_up": dense_init(ks[6], (L, E, h, f), h),
        "w_down": dense_init(ks[7], (L, E, f, h), f),
    }
    params = {
        "embed": dense_init(k_embed, (cfg.vocab_size, h), h),
        "layers": layers,
        "final_norm": norm_init((h,)),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_init(k_head, (h, cfg.vocab_size), h)
    return params


def param_logical_axes(cfg: MoeConfig) -> Dict[str, Any]:
    layers = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
        "router": ("layers", "embed", None),  # router replicated (tiny)
        "w_gate": ("layers", "expert", "embed", "mlp"),
        "w_up": ("layers", "expert", "embed", "mlp"),
        "w_down": ("layers", "expert", "mlp", "embed"),
    }
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _qeinsum(spec: str, x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """Expert einsum for a plain or int8-quantized weight (models/quant.py):
    the per-expert per-output-channel scale ([E, 1, out] after the layer
    slice) rescales the einsum RESULT, so no dequantized expert stack ever
    materializes — the same fusion argument as qmat."""
    from .quant import is_quantized

    if is_quantized(w):
        out = jnp.einsum(spec, x, w["q"].astype(x.dtype))
        return out * jnp.squeeze(w["s"], axis=-2).astype(out.dtype)
    return jnp.einsum(spec, x, w)


def moe_ffn(cfg: MoeConfig, lp: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
    """Top-k routed expert FFN, dense-compute sparse-weight.

    x: [..., hidden]; lp["router"]: [h, E]; experts [E, h, f]/[E, f, h].
    """
    k = cfg.experts_per_token
    logits = (x @ lp["router"]).astype(jnp.float32)  # [..., E]
    top_vals, top_idx = jax.lax.top_k(logits, k)  # [..., k]
    top_probs = jax.nn.softmax(top_vals, axis=-1)  # renormalized over top-k
    # scatter the k probabilities back to a dense [.., E] weight vector
    onehot = jax.nn.one_hot(top_idx, cfg.num_experts, dtype=jnp.float32)
    weights = jnp.einsum("...k,...ke->...e", top_probs, onehot)

    g = _qeinsum("...h,ehf->...ef", x, lp["w_gate"])
    u = _qeinsum("...h,ehf->...ef", x, lp["w_up"])
    act = (jax.nn.silu(g.astype(jnp.float32)).astype(x.dtype)) * u
    y = _qeinsum("...ef,efh->...eh", act, lp["w_down"])
    # contraction over E: with experts ep-sharded this is the one psum
    out = jnp.einsum("...eh,...e->...h", y.astype(jnp.float32), weights)
    return out.astype(x.dtype)
