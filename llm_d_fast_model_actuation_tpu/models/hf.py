"""Hugging Face checkpoint import: config + safetensors -> our param tree.

The reference actuates vLLM servers, which load Hugging Face model
directories directly (`--model <hf-dir>`); a user switching to this
framework brings the same directories. This module maps an HF Llama-family
checkpoint (config.json + *.safetensors) onto the stacked-layer param tree
`models/llama.py` scans over, so `--model hf:<dir>` serves the same weights.

Supported architectures: LlamaForCausalLM (Llama 2/3, TinyLlama),
MistralForCausalLM, Qwen2ForCausalLM (q/k/v biases), Qwen3ForCausalLM
(per-head q/k norms), GemmaForCausalLM, MixtralForCausalLM (routed MoE:
expert stacks + router, models/moe.py). Numeric parity with the
`transformers` forward pass is pinned by `tests/test_hf_import.py`.

Layout notes:
  * HF stores per-layer `model.layers.{i}.<name>.weight` with shape
    (out, in); our tree stacks all layers into one (L, in, out) array per
    weight (transpose + stack) so one compiled `lax.scan` body serves
    every layer.
  * HF Llama checkpoints use the rotate-half RoPE layout, which is exactly
    `ops/rope.py`'s convention — weights copy over without re-permutation.
  * Gemma stores zero-centered RMSNorm weights (the (1+w) convention) and
    scales embeddings by sqrt(hidden); both map onto config knobs
    (`norm_offset`, `embed_scale`) — values copy verbatim.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

import numpy as np

from ..utils import faults, tracing
from .llama import LlamaConfig

#: HF `architectures[0]` -> config-knob overrides for our shared forward
ARCHITECTURES: Dict[str, Dict[str, Any]] = {
    "LlamaForCausalLM": {},
    "MistralForCausalLM": {},
    "Qwen2ForCausalLM": {"attn_bias": True},
    "Qwen3ForCausalLM": {"qk_norm": True},
    "GemmaForCausalLM": {
        "hidden_activation": "gelu",
        "norm_offset": 1.0,
        "embed_scale": True,
        # gemma ties embeddings by default, and config.json omits defaults
        "tie_embeddings": True,
    },
    "MixtralForCausalLM": {},
}


def _read_config(path: str) -> Dict[str, Any]:
    cfg_path = os.path.join(path, "config.json")
    if not os.path.isfile(cfg_path):
        raise FileNotFoundError(f"no config.json under {path!r}")
    with open(cfg_path) as f:
        return json.load(f)


def _int_list(v: Any) -> list:
    """HF eos_token_id may be an int or a list (Llama-3's [eos, eom,
    eot]); normalize to a list of ints."""
    if isinstance(v, list):
        return [int(t) for t in v]
    if isinstance(v, (int, float)):
        return [int(v)]
    return []


def config_from_hf(path: str, **overrides: Any) -> LlamaConfig:
    """Build a LlamaConfig from an HF model directory's config.json.

    `overrides` lets callers force serving knobs (dtype, attention_impl,
    quantization, max_seq_len) without a second config source.
    """
    import dataclasses

    hf = _read_config(path)
    archs = hf.get("architectures") or []
    arch = archs[0] if archs else "LlamaForCausalLM"
    if arch not in ARCHITECTURES:
        raise ValueError(
            f"unsupported architecture {arch!r}; supported: "
            f"{sorted(ARCHITECTURES)}"
        )
    base: LlamaConfig = LlamaConfig()
    if arch == "MixtralForCausalLM":
        from .moe import MoeConfig

        base = MoeConfig()
    heads = int(hf["num_attention_heads"])
    hidden = int(hf["hidden_size"])
    fields: Dict[str, Any] = {
        "vocab_size": int(hf["vocab_size"]),
        "hidden_size": hidden,
        "num_layers": int(hf["num_hidden_layers"]),
        "num_heads": heads,
        "num_kv_heads": int(hf.get("num_key_value_heads", heads)),
        "head_dim": int(hf.get("head_dim") or hidden // heads),
        "intermediate_size": int(hf["intermediate_size"]),
        "rope_theta": float(hf.get("rope_theta", 10000.0)),
        "rms_eps": float(hf.get("rms_norm_eps", 1e-5)),
        "max_seq_len": int(hf.get("max_position_embeddings", 8192)),
    }
    scaling = hf.get("rope_scaling")
    if scaling:
        rtype = scaling.get("rope_type") or scaling.get("type")
        if rtype == "llama3":
            fields["rope_scaling"] = (
                "llama3",
                float(scaling["factor"]),
                float(scaling["low_freq_factor"]),
                float(scaling["high_freq_factor"]),
                int(scaling["original_max_position_embeddings"]),
            )
        elif rtype == "linear":
            fields["rope_scaling"] = ("linear", float(scaling["factor"]))
        elif rtype not in (None, "default"):
            # an ignored scaling spec would serve silently-wrong logits
            raise ValueError(
                f"unsupported rope_scaling type {rtype!r} "
                "(supported: llama3, linear)"
            )
    sw = hf.get("sliding_window")
    if sw:
        # Mistral-style sliding-window attention: within the window our
        # full attention is exactly equivalent, so cap the servable
        # context at the window instead of silently attending past it.
        fields["max_seq_len"] = min(fields["max_seq_len"], int(sw))
    if arch == "MixtralForCausalLM":
        fields["num_experts"] = int(hf["num_local_experts"])
        fields["experts_per_token"] = int(hf["num_experts_per_tok"])
    arch_defaults = dict(ARCHITECTURES[arch])
    fields["tie_embeddings"] = bool(
        hf.get(
            "tie_word_embeddings", arch_defaults.pop("tie_embeddings", False)
        )
    )
    fields.update(arch_defaults)
    fields.update(overrides)
    return dataclasses.replace(base, **fields)


def eos_token_ids_from_hf(path: str) -> list:
    """ALL declared eos ids (config.json union generation_config.json,
    order-preserving) — Llama-3-Instruct ends chat turns with <|eot_id|>,
    which is a SECOND eos id; stopping on just the first would decode
    every chat request to max_tokens. Empty when neither file declares
    one."""
    ids = _int_list(_read_config(path).get("eos_token_id"))
    gen_path = os.path.join(path, "generation_config.json")
    if os.path.isfile(gen_path):
        with open(gen_path) as f:
            for t in _int_list(json.load(f).get("eos_token_id")):
                if t not in ids:
                    ids.append(t)
    return ids


def eos_token_id_from_hf(path: str, default: int = 2) -> int:
    ids = eos_token_ids_from_hf(path)
    return ids[0] if ids else default


# -- weight loading ----------------------------------------------------------

#: bytes-in-flight bound for the streaming loader's host->device transfers
#: (~two 256 MiB buckets double-buffered, the same window discipline as
#: engine/sleep.py's chunked swap transfers)
DEFAULT_LOAD_INFLIGHT_BYTES = 512 << 20


class LoadAborted(RuntimeError):
    """A cold load / prefetch was cancelled through its abort event."""


@dataclasses.dataclass
class LoadStats:
    """Cold-load phase breakdown, filled in place by ``load_params(...,
    stats=...)``. Wall windows can overlap: ``overlap_s`` is the time both
    the disk-read pipeline and host->device transfers were in flight — the
    streaming win over a read-everything-then-transfer schedule."""

    total_s: float = 0.0
    read_s: float = 0.0  #: wall window: load start -> last tensor staged
    convert_s: float = 0.0  #: cumulative casted-copy time (sum over readers)
    h2d_s: float = 0.0  #: wall window: first transfer issued -> last landed
    overlap_s: float = 0.0
    overlap_frac: float = 0.0  #: overlap_s / total_s
    bytes_read: int = 0  #: native source bytes staged
    bytes_h2d: int = 0  #: device bytes transferred
    buckets_h2d: int = 0
    shards: int = 0
    workers: int = 0
    streaming: bool = False
    #: flat weight key -> content digest (engine/chunk_store.py), computed
    #: once per stacked buffer as its last slice lands — the identity the
    #: tiered pool dedupes on and the delta-swap matches by. Filled only
    #: with ``load_params(..., want_digests=True)``.
    digests: Dict[str, str] = dataclasses.field(default_factory=dict)

    def transfer_figures(self):
        """``(kind, bytes, seconds)`` rows for the cost oracle's
        bandwidth EWMAs (utils/costs.py): the disk-read and H2D windows
        this load already measured, in the kind vocabulary the oracle
        prices with. Zero-byte / zero-time windows are omitted."""
        out = []
        if self.bytes_read > 0 and self.read_s > 0:
            out.append(("coldload.read", self.bytes_read, self.read_s))
        if self.bytes_h2d > 0 and self.h2d_s > 0:
            out.append(("coldload.h2d", self.bytes_h2d, self.h2d_s))
        return out


def _shard_files(path: str) -> Tuple[str, List[str]]:
    """Resolve the checkpoint's shard layout WITHOUT reading tensor data:
    ``("safetensors" | "bin", ordered file list)``.

    A sharded checkpoint declares its shard set in the index file; a
    missing shard would otherwise just mean fewer tensors iterated (and
    silently zeroed layers, before load_params grew slice tracking). Fail
    up front — before any staging work — with the exact absent files."""
    st_files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    idx_path = os.path.join(path, "model.safetensors.index.json")
    if os.path.isfile(idx_path):
        with open(idx_path) as f:
            declared = sorted(set(json.load(f).get("weight_map", {}).values()))
        present = set(st_files)
        absent = [s for s in declared if s not in present]
        if absent:
            raise FileNotFoundError(
                f"checkpoint {path!r} index declares shard files that are "
                f"not present: {absent}"
            )
        # iterate exactly the declared shard set: directories often carry
        # extra safetensors (consolidated.*, partial downloads) that are
        # not part of the indexed checkpoint
        if declared:
            st_files = declared
    if st_files:
        return "safetensors", st_files
    bin_files = sorted(
        f
        for f in os.listdir(path)
        if f.startswith("pytorch_model") and f.endswith(".bin")
    )
    if not bin_files:
        raise FileNotFoundError(
            f"no *.safetensors or pytorch_model*.bin under {path!r}"
        )
    return "bin", bin_files


def _native_numpy(t) -> np.ndarray:
    """torch tensor -> numpy in the tensor's OWN dtype. bfloat16 (which
    numpy cannot express natively) goes through a bit-level uint16 view
    onto ml_dtypes.bfloat16 — never an fp32 copy. Every tensor the loader
    stages passes through here, so this is the choke point the
    no-fp32-transient regression test instruments."""
    import torch

    if t.layout != torch.strided:
        t = t.to_dense()
    if t.dtype == torch.bfloat16:
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def _iter_shard_tensors(
    path: str, kind: str, fname: str
) -> Iterator[Tuple[str, np.ndarray]]:
    """Yield (hf_name, native-dtype numpy array) for one shard file.
    safetensors shards are mmap-backed (one tensor resident at a time);
    the legacy .bin path drops each tensor's state-dict reference as it is
    consumed, so its peak host memory matches the safetensors path's
    one-tensor transient instead of holding the whole shard alive."""
    if kind == "safetensors":
        from safetensors import safe_open

        with safe_open(
            os.path.join(path, fname), framework="pt", device="cpu"
        ) as f:
            for name in f.keys():
                yield name, _native_numpy(f.get_tensor(name))
        return
    import torch

    sd = torch.load(
        os.path.join(path, fname), map_location="cpu", weights_only=True
    )
    for name in sorted(sd.keys()):
        yield name, _native_numpy(sd.pop(name))


def _iter_tensors(path: str) -> Iterator[Tuple[str, np.ndarray]]:
    """Yield (hf_name, native-dtype numpy array) over every tensor in the
    checkpoint, shard by shard (single-file, indexed-shard, or legacy
    pytorch_model.bin layouts)."""
    kind, files = _shard_files(path)
    for fname in files:
        yield from _iter_shard_tensors(path, kind, fname)


#: per-layer HF suffix -> (our key, transpose?)
_LAYER_MAP: Dict[str, Tuple[str, bool]] = {
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
    "self_attn.q_norm.weight": ("q_norm", False),
    "self_attn.k_norm.weight": ("k_norm", False),
    "input_layernorm.weight": ("attn_norm", False),
    "post_attention_layernorm.weight": ("mlp_norm", False),
}

#: mixtral block-sparse FFN: per-expert suffix -> (our key, transpose?)
_EXPERT_MAP: Dict[str, Tuple[str, bool]] = {
    "w1.weight": ("w_gate", True),
    "w2.weight": ("w_down", True),
    "w3.weight": ("w_up", True),
}

#: harmless checkpoint extras (precomputed buffers, not weights)
_IGNORED_SUFFIXES = ("rotary_emb.inv_freq",)

_TOP_MAP: Dict[str, Tuple[str, bool]] = {
    "model.embed_tokens.weight": ("embed", False),
    "model.norm.weight": ("final_norm", False),
    "lm_head.weight": ("lm_head", True),
}


def _route(
    name: str, tie_embeddings: bool
) -> Optional[Tuple[Tuple[str, ...], Optional[int], Optional[int], bool]]:
    """Map an HF tensor name -> (tree_key, layer, expert, transpose);
    None for deliberately-ignored tensors (precomputed buffers, tied
    lm_head); ValueError for anything unrecognized — a silently-dropped
    weight would serve wrong logits."""
    if name in _TOP_MAP:
        ours, transpose = _TOP_MAP[name]
        if ours == "lm_head" and tie_embeddings:
            return None  # tied: the forward reuses embed.T
        return (ours,), None, None, transpose
    if not name.startswith("model.layers."):
        if name.endswith(_IGNORED_SUFFIXES):
            return None
        raise ValueError(f"unrecognized checkpoint tensor {name!r}")
    rest = name[len("model.layers.") :]
    idx, _, suffix = rest.partition(".")
    if not idx.isdigit():
        raise ValueError(f"unrecognized checkpoint tensor {name!r}")
    layer = int(idx)
    if suffix in _LAYER_MAP:
        ours, transpose = _LAYER_MAP[suffix]
        return ("layers", ours), layer, None, transpose
    if suffix == "block_sparse_moe.gate.weight":
        return ("layers", "router"), layer, None, True
    if suffix.startswith("block_sparse_moe.experts."):
        rest2 = suffix[len("block_sparse_moe.experts.") :]
        e_str, _, w = rest2.partition(".")
        if w not in _EXPERT_MAP:
            raise ValueError(f"unrecognized expert tensor {name!r}")
        ours, transpose = _EXPERT_MAP[w]
        return ("layers", ours), layer, int(e_str), transpose
    if suffix.endswith(_IGNORED_SUFFIXES):
        return None
    raise ValueError(f"unrecognized checkpoint tensor {name!r}")


def _want_slices(flat: str, node: Any, n_experts: int) -> Set[tuple]:
    """Every (layer[, expert]) slice the model expects a checkpoint tensor
    to write for this stacked key (``("*",)`` = one whole-key write)."""
    parts = flat.split("/")
    if parts[0] == "layers":
        n_layers = node.shape[0]
        if n_experts and parts[-1] in ("w_gate", "w_up", "w_down"):
            return {
                (l, e) for l in range(n_layers) for e in range(n_experts)
            }
        return {(l,) for l in range(n_layers)}
    return {("*",)}


def _flat_targets(cfg: LlamaConfig, shapes: Dict[str, Any], mesh) -> Dict[str, Any]:
    """Per-flat-key device_put target: the serving NamedSharding on a mesh
    (same logical-axis rules the engine serves with), the default device
    otherwise."""
    import jax

    if mesh is None:
        dev = jax.devices()[0]
        return {"/".join(p): dev for p, _ in _flatten(shapes)}
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import named_sharding
    from .registry import logical_axes_for

    axes = {"/".join(p): ax for p, ax in _flatten(logical_axes_for(cfg))}
    return {
        "/".join(p): (
            NamedSharding(mesh, P())
            if axes.get("/".join(p)) is None
            else named_sharding(mesh, axes["/".join(p)])
        )
        for p, _ in _flatten(shapes)
    }


def _quantize_and_repin(
    cfg: LlamaConfig, params: Dict[str, Any], mesh: Any
) -> Dict[str, Any]:
    """Shared device-placement epilogue: apply the config's runtime
    quantization and — because the eager quantize ops don't all preserve
    the serving sharding (scale reductions in particular) — re-pin the
    quantized tree onto the mesh."""
    from .registry import maybe_quantize

    params = maybe_quantize(cfg, params)
    if getattr(cfg, "quantization", "") and mesh is not None:
        from ..parallel.mesh import shard_pytree

        from .registry import logical_axes_for

        params = shard_pytree(params, mesh, logical_axes_for(cfg))
    return params


def load_params(
    path: str,
    cfg: LlamaConfig,
    *,
    mesh: Any = None,
    workers: Optional[int] = None,
    streaming: Optional[bool] = None,
    place: bool = True,
    max_inflight_bytes: Optional[int] = None,
    abort_event: Optional[threading.Event] = None,
    throttle_bytes_per_s: float = 0.0,
    stats: Optional[LoadStats] = None,
    want_digests: bool = False,
) -> Dict[str, Any]:
    """Load an HF checkpoint into the stacked (L, ...) param tree — the
    pipelined, parallel cold-start path.

    Three overlapped stages:
      * **parallel shard readers** — a bounded thread pool over shard
        files (``workers``; <=0/None = auto). safetensors shards are
        mmap-backed and both the rust reads and the numpy casted copies
        release the GIL, so readers genuinely run concurrently.
      * **direct dtype staging** — each tensor is casted-copied from its
        NATIVE source dtype straight into the cfg.dtype stacked buffer
        (bfloat16 via ml_dtypes bit views): the per-tensor fp32 transient
        of the old loader is gone, and peak host memory stays ~one model
        in target dtype plus one source tensor.
      * **streaming device placement** (``streaming``, default on when
        ``place``) — the moment a stacked buffer's last slice lands, its
        host->device transfer is issued on a dedicated thread, bucketed
        and double-buffered with in-flight bytes bounded by
        ``max_inflight_bytes`` (engine/sleep.py's transfer discipline), so
        the disk read of layer k+1 overlaps the H2D of layer k. Each
        buffer's host copy is freed as its transfer lands.

    ``streaming=False`` runs the identical machinery on a strictly
    sequential schedule (all reads, then all transfers) — the paired
    baseline ``bench.py coldload`` compares against. ``place=False``
    skips device placement entirely and returns the host-staged plain
    (unquantized) numpy tree — the background-prefetch path, which must
    never touch HBM; ``place_staged_params`` is its deferred second half.

    ``abort_event`` (checked between tensors) raises LoadAborted;
    ``throttle_bytes_per_s`` bounds read bandwidth (prefetch I/O
    throttle). ``stats`` (a LoadStats) is filled in place.

    ``want_digests`` computes each stacked buffer's content digest
    (engine/chunk_store.py) the moment its last slice lands — on the
    reader threads, so hashing overlaps other shards' reads and (in
    streaming mode) the H2D stream — into ``stats.digests``. This is the
    ONE place weight content is hashed: the tiered pool's dedup and the
    delta-swap both reuse these digests.

    Bit-exactness: staging writes disjoint slices whose values do not
    depend on schedule, so any (workers, streaming) combination produces
    the same tree as the sequential loader.
    """
    import jax

    from .registry import init_params_for  # shape source of truth

    t_begin = time.monotonic()
    st = stats if stats is not None else LoadStats()
    # eval_shape over the UNquantized tree: staging happens in cfg.dtype,
    # quantization (if any) runs once at the end like the serving path
    plain = (
        dataclasses.replace(cfg, quantization="")
        if getattr(cfg, "quantization", "")
        else cfg
    )
    shapes = jax.eval_shape(
        lambda: init_params_for(jax.random.key(0), plain)
    )
    np_dtype = np.dtype(cfg.dtype)  # ml_dtypes registers bfloat16
    # shard layout first: a declared-but-absent shard must fail before any
    # staging work starts
    kind, files = _shard_files(path)
    if workers is None or int(workers) <= 0:
        workers = min(8, os.cpu_count() or 1)
    workers = max(1, min(int(workers), len(files)))
    if streaming is None:
        streaming = place
    streaming = bool(streaming and place)
    inflight_bound = int(max_inflight_bytes or DEFAULT_LOAD_INFLIGHT_BYTES)
    st.workers, st.shards, st.streaming = workers, len(files), streaming
    # Cold-load tracing (utils/tracing.py): one root span for the whole
    # load; shard reads and H2D buckets are child spans. Reader threads
    # and the transfer thread get the parent EXPLICITLY — ContextVars do
    # not cross thread starts.
    load_sp = tracing.begin(
        "coldload.load",
        activate=False,
        path=path,
        shards=len(files),
        workers=workers,
        streaming=streaming,
        place=place,
    )
    traced = load_sp is not tracing.NOOP_SPAN
    load_ctx = load_sp.context() if traced else None

    flat_shapes = {"/".join(p): n for p, n in _flatten(shapes)}
    n_experts = int(getattr(cfg, "num_experts", 0) or 0)
    want = {k: _want_slices(k, n, n_experts) for k, n in flat_shapes.items()}

    buffers: Dict[str, np.ndarray] = {}
    # Stacked buffers start zeroed, so "the key exists" is not evidence the
    # checkpoint supplied every layer/expert slice — a shard missing from an
    # un-indexed checkpoint would serve zeroed layers. Track exactly which
    # slices each staged tensor wrote; completeness is checked per slice
    # below. (transformers/vLLM get this via the safetensors index; we also
    # verify that in _shard_files when the index file exists.)
    staged: Dict[str, set] = {k: set() for k in flat_shapes}
    remaining = {k: len(s) for k, s in want.items()}
    mu = threading.Lock()
    ready: "queue.Queue[Optional[str]]" = queue.Queue()
    tie = bool(getattr(cfg, "tie_embeddings", False))
    convert_s = [0.0]
    bytes_read = [0]
    stop = threading.Event()  # internal: first reader error stops siblings

    def _aborted() -> bool:
        return stop.is_set() or (
            abort_event is not None and abort_event.is_set()
        )

    def stage(name: str, arr: np.ndarray) -> None:
        route = _route(name, tie)
        if route is None:
            return
        tree_key, layer, expert, transpose = route
        node = shapes
        for k in tree_key:
            if not isinstance(node, dict) or k not in node:
                # a tensor the config does not expect would be silently
                # dropped weight otherwise (e.g. biases with
                # attn_bias=False, q_norm without qk_norm)
                raise ValueError(
                    f"checkpoint tensor {name} has no "
                    f"place in the model config (architecture mismatch?)"
                )
            node = node[k]
        flat = "/".join(tree_key)
        if transpose:
            arr = arr.T
        if expert is not None:
            want_shape, sl = node.shape[2:], (layer, expert)
        elif layer is not None:
            want_shape, sl = node.shape[1:], (layer,)
        else:
            want_shape, sl = node.shape, ("*",)
        if arr.shape != tuple(want_shape):
            raise ValueError(
                f"{flat}: checkpoint shape {arr.shape} != model "
                f"{tuple(want_shape)}"
            )
        with mu:
            buf = buffers.get(flat)
            if buf is None:
                buf = buffers[flat] = np.zeros(node.shape, dtype=np_dtype)
        t0 = time.monotonic()
        # the ONLY conversion on the path: a casted copy from the native
        # source dtype into the cfg.dtype buffer slice (no fp32 transient;
        # disjoint slices, so concurrent readers need no lock here)
        if sl == ("*",):
            buf[...] = arr
        elif expert is not None:
            buf[layer, expert] = arr
        else:
            buf[layer] = arr
        dt = time.monotonic() - t0
        completed = False
        with mu:
            convert_s[0] += dt
            bytes_read[0] += arr.nbytes
            got = staged[flat]
            if sl not in got:
                got.add(sl)
                remaining[flat] -= 1
                completed = remaining[flat] == 0
        if completed:
            if want_digests:
                # hashed HERE — before the buffer is queued for transfer
                # (the streaming thread frees host buffers as they land),
                # and off the lock so sibling readers keep staging
                from ..engine.chunk_store import leaf_digest

                dg = leaf_digest(buf)
                with mu:
                    st.digests[flat] = dg
            if streaming:
                ready.put(flat)

    throttle_t0 = time.monotonic()

    def read_shard(fname: str) -> None:
        sp = (
            tracing.begin(
                "coldload.read_shard", parent=load_ctx, activate=False,
                shard=fname,
            )
            if traced
            else None
        )
        try:
            _read_shard(fname)
            if sp is not None:
                sp.end()
        except LoadAborted:
            # the failing shard is exactly what a failed-load trace must
            # show: record it with the error before unwinding
            if sp is not None:
                sp.set(error="aborted")
                sp.end()
            raise
        except BaseException as e:
            # fail fast from INSIDE the failing worker: the main thread
            # collects futures in submission order, so without this a
            # wrong tensor in the last shard would let every earlier
            # shard read (and stream to device) to completion first
            stop.set()
            if sp is not None:
                sp.set(error=f"{type(e).__name__}: {e}")
                sp.end()
            raise

    def _read_shard(fname: str) -> None:
        faults.fire("coldload.read")
        for name, arr in _iter_shard_tensors(path, kind, fname):
            if _aborted():
                raise LoadAborted(f"load of {path!r} aborted")
            stage(name, arr)
            if throttle_bytes_per_s and throttle_bytes_per_s > 0:
                with mu:
                    b = bytes_read[0]
                ahead = b / throttle_bytes_per_s - (
                    time.monotonic() - throttle_t0
                )
                while ahead > 0 and not _aborted():
                    time.sleep(min(ahead, 0.2))
                    ahead = b / throttle_bytes_per_s - (
                        time.monotonic() - throttle_t0
                    )

    # -- streaming h2d transfer thread (bucketed, double-buffered) ----------
    placed: Dict[str, Any] = {}
    xfer_err: List[BaseException] = []
    h2d_win: List[Optional[float]] = [None, None]
    h2d_counts = [0, 0]  # buckets, bytes
    targets = _flat_targets(plain, shapes, mesh) if place else {}

    def run_transfers() -> None:
        from ..engine.sleep import partition_buckets

        # double-buffered: bucket k+1 is issued while bucket k drains, so
        # in-flight bytes stay ~<= inflight_bound (two buckets)
        bucket_bytes = max(1, inflight_bound // 2)
        pending = None  # (flats, puts, nbytes, span)

        def finish(p) -> None:
            flats, puts, nb, sp = p
            try:
                puts = jax.block_until_ready(puts)
            except BaseException as e:
                if sp is not None:
                    sp.set(error=f"{type(e).__name__}: {e}")
                    sp.end()
                raise
            with mu:
                for f, a in zip(flats, puts):
                    placed[f] = a
                    buffers.pop(f, None)  # host copy freed as it lands
            h2d_counts[0] += 1
            h2d_counts[1] += nb
            h2d_win[1] = time.monotonic()
            if sp is not None:
                sp.end()

        try:
            draining = False
            while not draining:
                item = ready.get()
                if item is None:
                    break
                flats = [item]
                while True:
                    try:
                        nxt = ready.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        draining = True
                        break
                    flats.append(nxt)
                with mu:
                    arrs = {f: buffers[f] for f in flats}
                nbs = [arrs[f].nbytes for f in flats]
                for bucket in partition_buckets(nbs, bucket_bytes):
                    bflats = [flats[i] for i in bucket]
                    bsp = (
                        tracing.begin(
                            "coldload.h2d", parent=load_ctx,
                            activate=False,
                            bytes=sum(nbs[i] for i in bucket),
                            leaves=len(bflats),
                        )
                        if traced
                        else None
                    )
                    try:
                        faults.fire("coldload.h2d")
                        if h2d_win[0] is None:
                            h2d_win[0] = time.monotonic()
                        puts = jax.device_put(
                            [arrs[f] for f in bflats],
                            [targets[f] for f in bflats],
                        )
                    except BaseException as e:
                        if bsp is not None:
                            bsp.set(error=f"{type(e).__name__}: {e}")
                            bsp.end()
                        raise
                    cur = (bflats, puts, sum(nbs[i] for i in bucket), bsp)
                    if pending is not None:
                        finish(pending)
                    pending = cur
            if pending is not None:
                pending_, pending = pending, None
                finish(pending_)
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            if (
                pending is not None
                and pending[3] is not None
                and not pending[3].ended
            ):
                # the double-buffered predecessor never finished: record
                # it as cut short so the failed load's trace is complete
                # (a span finish() already failed keeps its real error)
                pending[3].set(error="aborted by transfer failure")
                pending[3].end()
            xfer_err.append(e)

    xfer_thread = None
    if place:
        xfer_thread = threading.Thread(
            target=run_transfers, name="hf-load-h2d", daemon=True
        )
        xfer_thread.start()

    # -- reads ---------------------------------------------------------------
    err: Optional[BaseException] = None
    try:
        if workers == 1:
            for fname in files:
                read_shard(fname)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                workers, thread_name_prefix="hf-load-read"
            ) as pool:
                futs = [pool.submit(read_shard, f) for f in files]
                for fut in futs:
                    try:
                        fut.result()
                    except LoadAborted as e:
                        if err is None:
                            err = e
                    except BaseException as e:  # noqa: BLE001
                        # the first REAL error wins (in file order);
                        # sibling LoadAborted from the stop signal is noise
                        if err is None or isinstance(err, LoadAborted):
                            err = e
                        stop.set()
    except BaseException as e:  # noqa: BLE001 — single-worker path
        err = e
    read_t1 = time.monotonic()

    if err is None:
        # Per-slice completeness: every (key, layer[, expert]) the model
        # expects must have been written by some checkpoint tensor —
        # whole-key presence is not enough (stacked buffers zero-init, so
        # one staged layer would mask the rest being absent).
        problems = []
        for flat in flat_shapes:
            absent = want[flat] - staged[flat]
            if absent:
                ex = sorted(absent)[:4]
                problems.append(
                    f"{flat}: {len(absent)}/{len(want[flat])} slices never "
                    f"staged (e.g. {ex})"
                )
        if problems:
            err = ValueError(
                f"checkpoint {path!r} is incomplete: "
                + "; ".join(sorted(problems))
            )

    if place:
        if err is None and not streaming:
            # sequential schedule: every transfer happens after every read
            for flat in flat_shapes:
                ready.put(flat)
        ready.put(None)
        xfer_thread.join()
        if err is None and xfer_err:
            err = xfer_err[0]
    # read-side stats are valid even on the error paths (an aborted
    # prefetch reports how many bytes it actually spent)
    st.read_s = read_t1 - t_begin
    st.convert_s = convert_s[0]
    st.bytes_read = bytes_read[0]
    if err is not None:
        load_sp.set(error=f"{type(err).__name__}: {err}")
        load_sp.end()
        raise err

    if not place:
        st.total_s = time.monotonic() - t_begin
        load_sp.set(bytes_read=st.bytes_read)
        load_sp.end()
        return _unflatten(dict(buffers))

    st.h2d_s = (
        (h2d_win[1] - h2d_win[0]) if h2d_win[0] is not None else 0.0
    )
    st.buckets_h2d, st.bytes_h2d = h2d_counts
    params = _quantize_and_repin(cfg, _unflatten(placed), mesh)
    st.total_s = time.monotonic() - t_begin
    # overlap: time the read pipeline and the h2d stream were BOTH in
    # flight — what the streaming schedule saves over read-then-transfer
    if h2d_win[0] is not None:
        st.overlap_s = max(
            0.0, min(read_t1, h2d_win[1]) - max(t_begin, h2d_win[0])
        )
    st.overlap_frac = st.overlap_s / st.total_s if st.total_s > 0 else 0.0
    load_sp.set(
        bytes_read=st.bytes_read,
        bytes_h2d=st.bytes_h2d,
        buckets_h2d=st.buckets_h2d,
        overlap_frac=round(st.overlap_frac, 6),
    )
    load_sp.end()
    return params


def place_staged_params(
    staged: Dict[str, Any],
    cfg: LlamaConfig,
    *,
    mesh: Any = None,
    max_inflight_bytes: Optional[int] = None,
    stats: Optional[LoadStats] = None,
) -> Dict[str, Any]:
    """The H2D half of the streaming loader, standalone: device-place a
    host tree produced by ``load_params(..., place=False)`` (the prefetch
    path), bucketed and double-buffered with the same in-flight bound.
    The host arrays are left intact (the caller owns them)."""
    import jax

    from ..engine.sleep import partition_buckets

    t_begin = time.monotonic()
    st = stats if stats is not None else LoadStats()
    plain = (
        dataclasses.replace(cfg, quantization="")
        if getattr(cfg, "quantization", "")
        else cfg
    )
    flat = {"/".join(p): a for p, a in _flatten(staged)}
    targets = _flat_targets(plain, staged, mesh)
    keys = list(flat)
    nbs = [flat[k].nbytes for k in keys]
    bucket_bytes = max(
        1, int(max_inflight_bytes or DEFAULT_LOAD_INFLIGHT_BYTES) // 2
    )
    placed: Dict[str, Any] = {}
    pending = None
    stage_sp = tracing.begin(
        "coldload.place_staged", activate=False, leaves=len(keys)
    )
    traced = stage_sp is not tracing.NOOP_SPAN
    stage_ctx = stage_sp.context() if traced else None

    def finish(p) -> None:
        bkeys, puts, nb, sp = p
        try:
            puts = jax.block_until_ready(puts)
        except BaseException as e:
            if sp is not None:
                sp.set(error=f"{type(e).__name__}: {e}")
                sp.end()
            raise
        for k, a in zip(bkeys, puts):
            placed[k] = a
        st.buckets_h2d += 1
        st.bytes_h2d += nb
        if sp is not None:
            sp.end()

    try:
        for bucket in partition_buckets(nbs, bucket_bytes):
            bkeys = [keys[i] for i in bucket]
            bsp = (
                tracing.begin(
                    "coldload.h2d", parent=stage_ctx, activate=False,
                    bytes=sum(nbs[i] for i in bucket), leaves=len(bkeys),
                )
                if traced
                else None
            )
            try:
                faults.fire("coldload.h2d")
                puts = jax.device_put(
                    [flat[k] for k in bkeys], [targets[k] for k in bkeys]
                )
            except BaseException as e:
                if bsp is not None:
                    bsp.set(error=f"{type(e).__name__}: {e}")
                    bsp.end()
                raise
            cur = (bkeys, puts, sum(nbs[i] for i in bucket), bsp)
            if pending is not None:
                finish(pending)
            pending = cur
        if pending is not None:
            pending_, pending = pending, None
            finish(pending_)
    except BaseException as e:
        if (
            pending is not None
            and pending[3] is not None
            and not pending[3].ended
        ):
            pending[3].set(error="aborted by transfer failure")
            pending[3].end()
        stage_sp.set(error=f"{type(e).__name__}: {e}")
        stage_sp.end()
        raise

    params = _quantize_and_repin(cfg, _unflatten(placed), mesh)
    st.h2d_s = st.total_s = time.monotonic() - t_begin
    stage_sp.set(bytes_h2d=st.bytes_h2d, buckets_h2d=st.buckets_h2d)
    stage_sp.end()
    return params


def estimate_param_bytes(
    cfg: LlamaConfig,
    transfer_quant: str = "off",
    hot_head: bool = True,
) -> int:
    """Host bytes a staged copy of the model occupies — the prefetch
    budget pre-check. Shapes only; nothing read.

    ``transfer_quant`` ("int8"/"fp8", --sleep-quant) sizes the leaves the
    compressed staging path quantizes at their payload+scale bytes instead
    of cfg.dtype — without it the admission check would over-reserve ~2x
    for an int8-staged model and reject prefetches that actually fit."""
    import jax

    from .registry import init_params_for
    from . import quant as quant_mod

    plain = (
        dataclasses.replace(cfg, quantization="")
        if getattr(cfg, "quantization", "")
        else cfg
    )
    shapes = jax.eval_shape(
        lambda: init_params_for(jax.random.key(0), plain)
    )
    itemsize = np.dtype(cfg.dtype).itemsize
    mode = transfer_quant if transfer_quant not in ("", "off") else ""
    if not mode:
        return sum(
            int(np.prod(node.shape)) * itemsize
            for _, node in _flatten(shapes)
        )
    import jax.tree_util as jtu

    flat_leaves = jtu.tree_flatten(shapes)[0]
    plan = quant_mod.transfer_quant_plan(shapes, hot_head=hot_head, prefix="")
    total = 0
    for leaf, q in zip(flat_leaves, plan):
        if q:
            total += quant_mod.payload_nbytes(leaf.shape, mode)
        else:
            total += int(np.prod(leaf.shape)) * itemsize
    return total


def load_model(
    path: str, **overrides: Any
) -> Tuple[LlamaConfig, Dict[str, Any]]:
    cfg = config_from_hf(path, **overrides)
    return cfg, load_params(path, cfg)


def _flatten(tree: Dict[str, Any], prefix: Tuple[str, ...] = ()):
    for k, v in tree.items():
        if isinstance(v, dict):
            yield from _flatten(v, prefix + (k,))
        else:
            yield prefix + (k,), v


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    # one definition of the '/'-joined flat-key convention, shared with
    # the digest maps / tier manifests (lazy import: parse-time must not
    # pull the engine package)
    from ..engine.chunk_store import unflatten_tree

    return unflatten_tree(flat)
