"""Hugging Face checkpoint import: config + safetensors -> our param tree.

The reference actuates vLLM servers, which load Hugging Face model
directories directly (`--model <hf-dir>`); a user switching to this
framework brings the same directories. This module maps an HF Llama-family
checkpoint (config.json + *.safetensors) onto the stacked-layer param tree
`models/llama.py` scans over, so `--model hf:<dir>` serves the same weights.

Supported architectures: LlamaForCausalLM (Llama 2/3, TinyLlama),
MistralForCausalLM, Qwen2ForCausalLM (q/k/v biases), Qwen3ForCausalLM
(per-head q/k norms), GemmaForCausalLM, MixtralForCausalLM (routed MoE:
expert stacks + router, models/moe.py). Numeric parity with the
`transformers` forward pass is pinned by `tests/test_hf_import.py`.

Layout notes:
  * HF stores per-layer `model.layers.{i}.<name>.weight` with shape
    (out, in); our tree stacks all layers into one (L, in, out) array per
    weight (transpose + stack) so one compiled `lax.scan` body serves
    every layer.
  * HF Llama checkpoints use the rotate-half RoPE layout, which is exactly
    `ops/rope.py`'s convention — weights copy over without re-permutation.
  * Gemma stores zero-centered RMSNorm weights (the (1+w) convention) and
    scales embeddings by sqrt(hidden); both map onto config knobs
    (`norm_offset`, `embed_scale`) — values copy verbatim.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterator, Tuple

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig

#: HF `architectures[0]` -> config-knob overrides for our shared forward
ARCHITECTURES: Dict[str, Dict[str, Any]] = {
    "LlamaForCausalLM": {},
    "MistralForCausalLM": {},
    "Qwen2ForCausalLM": {"attn_bias": True},
    "Qwen3ForCausalLM": {"qk_norm": True},
    "GemmaForCausalLM": {
        "hidden_activation": "gelu",
        "norm_offset": 1.0,
        "embed_scale": True,
        # gemma ties embeddings by default, and config.json omits defaults
        "tie_embeddings": True,
    },
    "MixtralForCausalLM": {},
}


def _read_config(path: str) -> Dict[str, Any]:
    cfg_path = os.path.join(path, "config.json")
    if not os.path.isfile(cfg_path):
        raise FileNotFoundError(f"no config.json under {path!r}")
    with open(cfg_path) as f:
        return json.load(f)


def _int_list(v: Any) -> list:
    """HF eos_token_id may be an int or a list (Llama-3's [eos, eom,
    eot]); normalize to a list of ints."""
    if isinstance(v, list):
        return [int(t) for t in v]
    if isinstance(v, (int, float)):
        return [int(v)]
    return []


def config_from_hf(path: str, **overrides: Any) -> LlamaConfig:
    """Build a LlamaConfig from an HF model directory's config.json.

    `overrides` lets callers force serving knobs (dtype, attention_impl,
    quantization, max_seq_len) without a second config source.
    """
    import dataclasses

    hf = _read_config(path)
    archs = hf.get("architectures") or []
    arch = archs[0] if archs else "LlamaForCausalLM"
    if arch not in ARCHITECTURES:
        raise ValueError(
            f"unsupported architecture {arch!r}; supported: "
            f"{sorted(ARCHITECTURES)}"
        )
    base: LlamaConfig = LlamaConfig()
    if arch == "MixtralForCausalLM":
        from .moe import MoeConfig

        base = MoeConfig()
    heads = int(hf["num_attention_heads"])
    hidden = int(hf["hidden_size"])
    fields: Dict[str, Any] = {
        "vocab_size": int(hf["vocab_size"]),
        "hidden_size": hidden,
        "num_layers": int(hf["num_hidden_layers"]),
        "num_heads": heads,
        "num_kv_heads": int(hf.get("num_key_value_heads", heads)),
        "head_dim": int(hf.get("head_dim") or hidden // heads),
        "intermediate_size": int(hf["intermediate_size"]),
        "rope_theta": float(hf.get("rope_theta", 10000.0)),
        "rms_eps": float(hf.get("rms_norm_eps", 1e-5)),
        "max_seq_len": int(hf.get("max_position_embeddings", 8192)),
    }
    scaling = hf.get("rope_scaling")
    if scaling:
        rtype = scaling.get("rope_type") or scaling.get("type")
        if rtype == "llama3":
            fields["rope_scaling"] = (
                "llama3",
                float(scaling["factor"]),
                float(scaling["low_freq_factor"]),
                float(scaling["high_freq_factor"]),
                int(scaling["original_max_position_embeddings"]),
            )
        elif rtype == "linear":
            fields["rope_scaling"] = ("linear", float(scaling["factor"]))
        elif rtype not in (None, "default"):
            # an ignored scaling spec would serve silently-wrong logits
            raise ValueError(
                f"unsupported rope_scaling type {rtype!r} "
                "(supported: llama3, linear)"
            )
    sw = hf.get("sliding_window")
    if sw:
        # Mistral-style sliding-window attention: within the window our
        # full attention is exactly equivalent, so cap the servable
        # context at the window instead of silently attending past it.
        fields["max_seq_len"] = min(fields["max_seq_len"], int(sw))
    if arch == "MixtralForCausalLM":
        fields["num_experts"] = int(hf["num_local_experts"])
        fields["experts_per_token"] = int(hf["num_experts_per_tok"])
    arch_defaults = dict(ARCHITECTURES[arch])
    fields["tie_embeddings"] = bool(
        hf.get(
            "tie_word_embeddings", arch_defaults.pop("tie_embeddings", False)
        )
    )
    fields.update(arch_defaults)
    fields.update(overrides)
    return dataclasses.replace(base, **fields)


def eos_token_ids_from_hf(path: str) -> list:
    """ALL declared eos ids (config.json union generation_config.json,
    order-preserving) — Llama-3-Instruct ends chat turns with <|eot_id|>,
    which is a SECOND eos id; stopping on just the first would decode
    every chat request to max_tokens. Empty when neither file declares
    one."""
    ids = _int_list(_read_config(path).get("eos_token_id"))
    gen_path = os.path.join(path, "generation_config.json")
    if os.path.isfile(gen_path):
        with open(gen_path) as f:
            for t in _int_list(json.load(f).get("eos_token_id")):
                if t not in ids:
                    ids.append(t)
    return ids


def eos_token_id_from_hf(path: str, default: int = 2) -> int:
    ids = eos_token_ids_from_hf(path)
    return ids[0] if ids else default


# -- weight loading ----------------------------------------------------------


def _iter_tensors(path: str) -> Iterator[Tuple[str, np.ndarray]]:
    """Yield (hf_name, fp32 numpy array) over every tensor in the
    checkpoint, shard by shard (single-file, indexed-shard, or legacy
    pytorch_model.bin layouts)."""
    st_files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    # A sharded checkpoint declares its shard set in the index file; a
    # missing shard would otherwise just mean fewer tensors iterated (and
    # silently zeroed layers, before load_params grew slice tracking).
    # Fail up front with the exact files that are absent.
    idx_path = os.path.join(path, "model.safetensors.index.json")
    if os.path.isfile(idx_path):
        with open(idx_path) as f:
            declared = sorted(set(json.load(f).get("weight_map", {}).values()))
        present = set(st_files)
        absent = [s for s in declared if s not in present]
        if absent:
            raise FileNotFoundError(
                f"checkpoint {path!r} index declares shard files that are "
                f"not present: {absent}"
            )
        # iterate exactly the declared shard set: directories often carry
        # extra safetensors (consolidated.*, partial downloads) that are
        # not part of the indexed checkpoint
        if declared:
            st_files = declared
    if st_files:
        from safetensors import safe_open

        for fname in st_files:
            with safe_open(
                os.path.join(path, fname), framework="pt", device="cpu"
            ) as f:
                for name in f.keys():
                    t = f.get_tensor(name)
                    yield name, t.to_dense().float().numpy()
        return
    bin_files = sorted(
        f
        for f in os.listdir(path)
        if f.startswith("pytorch_model") and f.endswith(".bin")
    )
    if not bin_files:
        raise FileNotFoundError(
            f"no *.safetensors or pytorch_model*.bin under {path!r}"
        )
    import torch

    for fname in bin_files:
        sd = torch.load(
            os.path.join(path, fname), map_location="cpu", weights_only=True
        )
        for name, t in sd.items():
            yield name, t.float().numpy()


#: per-layer HF suffix -> (our key, transpose?)
_LAYER_MAP: Dict[str, Tuple[str, bool]] = {
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
    "self_attn.q_norm.weight": ("q_norm", False),
    "self_attn.k_norm.weight": ("k_norm", False),
    "input_layernorm.weight": ("attn_norm", False),
    "post_attention_layernorm.weight": ("mlp_norm", False),
}

#: mixtral block-sparse FFN: per-expert suffix -> (our key, transpose?)
_EXPERT_MAP: Dict[str, Tuple[str, bool]] = {
    "w1.weight": ("w_gate", True),
    "w2.weight": ("w_down", True),
    "w3.weight": ("w_up", True),
}

#: harmless checkpoint extras (precomputed buffers, not weights)
_IGNORED_SUFFIXES = ("rotary_emb.inv_freq",)

_TOP_MAP: Dict[str, Tuple[str, bool]] = {
    "model.embed_tokens.weight": ("embed", False),
    "model.norm.weight": ("final_norm", False),
    "lm_head.weight": ("lm_head", True),
}


def load_params(path: str, cfg: LlamaConfig) -> Dict[str, Any]:
    """Load an HF checkpoint into the stacked (L, ...) param tree.

    Tensors are staged per-layer into numpy buffers already in
    `cfg.dtype` (the only fp32 transient is the single tensor being
    converted), so peak host memory is ~one model in target dtype plus
    one tensor — not an fp32 copy of the whole model.
    """
    import jax

    from .registry import init_params_for  # shape source of truth

    import dataclasses

    # eval_shape over the UNquantized tree: staging happens in cfg.dtype,
    # quantization (if any) runs once at the end like the serving path
    plain = (
        dataclasses.replace(cfg, quantization="")
        if getattr(cfg, "quantization", "")
        else cfg
    )
    shapes = jax.eval_shape(
        lambda: init_params_for(jax.random.key(0), plain)
    )
    np_dtype = np.dtype(cfg.dtype)  # ml_dtypes registers bfloat16
    buffers: Dict[str, Any] = {}
    # Stacked buffers start zeroed, so "the key exists" is not evidence the
    # checkpoint supplied every layer/expert slice — a shard missing from an
    # un-indexed checkpoint would serve zeroed layers. Track exactly which
    # slices each staged tensor wrote; completeness is checked per slice
    # below. (transformers/vLLM get this via the safetensors index; we also
    # verify that in _iter_tensors when the index file exists.)
    staged: Dict[str, set] = {}

    def stage(
        tree_key: Tuple[str, ...],
        layer: int | None,
        arr: np.ndarray,
        expert: int | None = None,
        name: str = "",
    ):
        node = shapes
        for k in tree_key:
            if not isinstance(node, dict) or k not in node:
                # a tensor the config does not expect would be silently
                # dropped weight otherwise (e.g. biases with
                # attn_bias=False, q_norm without qk_norm)
                raise ValueError(
                    f"checkpoint tensor {name or '/'.join(tree_key)} has no "
                    f"place in the model config (architecture mismatch?)"
                )
            node = node[k]
        flat = "/".join(tree_key)
        if flat not in buffers:
            buffers[flat] = np.zeros(node.shape, dtype=np_dtype)
        if expert is not None:
            want, dst = node.shape[2:], lambda b: b[layer].__setitem__(
                expert, arr.astype(np_dtype)
            )
        elif layer is not None:
            want, dst = node.shape[1:], lambda b: b.__setitem__(
                layer, arr.astype(np_dtype)
            )
        else:
            want, dst = node.shape, lambda b: b.__setitem__(
                ..., arr.astype(np_dtype)
            )
        if arr.shape != tuple(want):
            raise ValueError(
                f"{flat}: checkpoint shape {arr.shape} != model {tuple(want)}"
            )
        dst(buffers[flat])
        if expert is not None:
            staged.setdefault(flat, set()).add((layer, expert))
        elif layer is not None:
            staged.setdefault(flat, set()).add((layer,))
        else:
            staged.setdefault(flat, set()).add(("*",))

    for name, arr in _iter_tensors(path):
        if name in _TOP_MAP:
            ours, transpose = _TOP_MAP[name]
            if ours == "lm_head" and cfg.tie_embeddings:
                continue  # tied: the forward reuses embed.T
            stage((ours,), None, arr.T if transpose else arr, name=name)
            continue
        if not name.startswith("model.layers."):
            if name.endswith(_IGNORED_SUFFIXES):
                continue
            raise ValueError(f"unrecognized checkpoint tensor {name!r}")
        rest = name[len("model.layers.") :]
        idx, _, suffix = rest.partition(".")
        if not idx.isdigit():
            raise ValueError(f"unrecognized checkpoint tensor {name!r}")
        layer = int(idx)
        if suffix in _LAYER_MAP:
            ours, transpose = _LAYER_MAP[suffix]
            stage(
                ("layers", ours), layer, arr.T if transpose else arr,
                name=name,
            )
        elif suffix == "block_sparse_moe.gate.weight":
            stage(("layers", "router"), layer, arr.T, name=name)
        elif suffix.startswith("block_sparse_moe.experts."):
            rest2 = suffix[len("block_sparse_moe.experts.") :]
            e_str, _, w = rest2.partition(".")
            if w not in _EXPERT_MAP:
                raise ValueError(f"unrecognized expert tensor {name!r}")
            ours, transpose = _EXPERT_MAP[w]
            stage(
                ("layers", ours), layer, arr.T if transpose else arr,
                expert=int(e_str), name=name,
            )
        elif suffix.endswith(_IGNORED_SUFFIXES):
            continue
        else:
            raise ValueError(f"unrecognized checkpoint tensor {name!r}")

    # Per-slice completeness: every (key, layer[, expert]) the model expects
    # must have been written by some checkpoint tensor — whole-key presence
    # is not enough (stacked buffers zero-init, so one staged layer would
    # mask the rest being absent).
    n_experts = int(getattr(cfg, "num_experts", 0) or 0)
    problems = []
    for p, node in _flatten(shapes):
        flat = "/".join(p)
        got = staged.get(flat, set())
        if ("*",) in got:
            continue
        if p[0] == "layers":
            n_layers = node.shape[0]
            if n_experts and p[-1] in ("w_gate", "w_up", "w_down"):
                want_slices = {
                    (l, e)
                    for l in range(n_layers)
                    for e in range(n_experts)
                }
            else:
                want_slices = {(l,) for l in range(n_layers)}
        else:
            want_slices = {("*",)}
        absent = want_slices - got
        if absent:
            ex = sorted(absent)[:4]
            problems.append(
                f"{flat}: {len(absent)}/{len(want_slices)} slices never "
                f"staged (e.g. {ex})"
            )
    if problems:
        raise ValueError(
            f"checkpoint {path!r} is incomplete: " + "; ".join(sorted(problems))
        )
    params = _unflatten(
        {k: jnp.asarray(v) for k, v in buffers.items()}
    )
    from .registry import maybe_quantize

    return maybe_quantize(cfg, params)


def load_model(path: str, **overrides: Any) -> Tuple[LlamaConfig, Dict[str, Any]]:
    cfg = config_from_hf(path, **overrides)
    return cfg, load_params(path, cfg)


def _flatten(tree: Dict[str, Any], prefix: Tuple[str, ...] = ()):
    for k, v in tree.items():
        if isinstance(v, dict):
            yield from _flatten(v, prefix + (k,))
        else:
            yield prefix + (k,), v


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out
