"""Llama-family decoder (Llama 2/3, TinyLlama, Qwen2-style GQA) — TPU-first.

Design choices for the TPU/XLA compilation model:
  * layer params are **stacked** on a leading layer axis and the forward is a
    single ``lax.scan`` over layers — one compiled layer body regardless of
    depth (compile time O(1) in layers, the win that matters for wake-up);
  * paged KV cache is threaded *through* the scan, so cache updates are
    in-place (donated) scatters fused into the step;
  * all matmuls bf16 on the MXU, softmax/norm math fp32;
  * tensor-parallel sharding is expressed via logical axes only
    (`param_logical_axes`); GSPMD inserts the all-reduces.

The flagship config mirrors Llama-3-8B (the reference's north-star model for
wake_up->TTFT, BASELINE.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import (
    causal_prefill_attention,
    paged_decode_attention,
    paged_decode_attention_inline,
    ragged_paged_attention,
)
from ..ops.norm import rms_norm
from ..ops.rope import apply_rope, rope_table
from .quant import qmat


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    intermediate_size: int = 14336
    rope_theta: float = 500000.0
    #: HF-style rope_scaling as a hashable tuple (ops/rope.py):
    #: ("linear", factor) or ("llama3", factor, low_ff, high_ff, orig_max).
    #: None = plain RoPE.
    rope_scaling: Any = None
    rms_eps: float = 1e-5
    max_seq_len: int = 8192
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    #: Attention implementation ("reference" | "pallas"); per-model so two
    #: engines in one process can't clobber each other's choice.
    attention_impl: str = "reference"
    #: Weight-only quantization: "" (bf16) or "int8" (W8A16 per-output-
    #: channel, models/quant.py) — halves decode's weight-read bytes.
    quantization: str = ""
    # -- Gemma-family knobs (llama-neutral defaults; one shared forward) --
    #: MLP gate activation: "silu" (llama/mixtral) or "gelu" (gemma GeGLU)
    hidden_activation: str = "silu"
    #: RMSNorm weight offset: 0.0 (llama) or 1.0 (gemma's (1+w) convention)
    norm_offset: float = 0.0
    #: sandwich norms: normalize attention/FFN outputs before the residual
    post_norms: bool = False
    #: scale embeddings by sqrt(hidden_size) (gemma)
    embed_scale: bool = False
    #: per-head RMSNorm on q and k before RoPE (gemma-3 style)
    qk_norm: bool = False
    #: biases on the q/k/v projections (Qwen2 convention)
    attn_bias: bool = False

    @classmethod
    def tiny_gemma(cls, vocab: int = 256) -> "LlamaConfig":
        """Gemma-3-style tiny config: GeGLU, (1+w) norms, sandwich norms,
        scaled embeddings, QK-norm, tied embeddings."""
        base = cls.tiny(vocab)
        import dataclasses

        return dataclasses.replace(
            base,
            hidden_activation="gelu",
            norm_offset=1.0,
            post_norms=True,
            embed_scale=True,
            qk_norm=True,
            tie_embeddings=True,
        )

    @classmethod
    def gemma3_4b(cls) -> "LlamaConfig":
        return cls(
            vocab_size=262144,
            hidden_size=2560,
            num_layers=34,
            num_heads=8,
            num_kv_heads=4,
            head_dim=256,
            intermediate_size=10240,
            rope_theta=1e6,
            max_seq_len=32768,
            tie_embeddings=True,
            hidden_activation="gelu",
            norm_offset=1.0,
            post_norms=True,
            embed_scale=True,
            qk_norm=True,
        )

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def llama3_70b(cls) -> "LlamaConfig":
        return cls(
            hidden_size=8192,
            num_layers=80,
            num_heads=64,
            num_kv_heads=8,
            intermediate_size=28672,
        )

    @classmethod
    def tiny(cls, vocab: int = 256) -> "LlamaConfig":
        """CPU-mesh test size."""
        return cls(
            vocab_size=vocab,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            intermediate_size=128,
            rope_theta=10000.0,
            max_seq_len=128,
        )

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def num_params(self) -> int:
        per_layer = (
            2 * self.hidden_size  # norms
            + self.hidden_size * self.q_dim
            + 2 * self.hidden_size * self.kv_dim
            + self.q_dim * self.hidden_size
            + 3 * self.hidden_size * self.intermediate_size
        )
        if self.post_norms:
            per_layer += 2 * self.hidden_size
        if self.qk_norm:
            per_layer += 2 * self.head_dim
        head = 0 if self.tie_embeddings else self.hidden_size * self.vocab_size
        return (
            self.vocab_size * self.hidden_size
            + self.num_layers * per_layer
            + self.hidden_size
            + head
        )


def init_params(key: jax.Array, cfg: LlamaConfig) -> Dict[str, Any]:
    """Random-init bf16 params (serving loads checkpoints; random init is for
    tests/benchmarks and shape-defining)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    h, L = cfg.hidden_size, cfg.num_layers

    def norm_init(shape):
        # Gemma's (1+w) convention stores zero-centered weights: identity
        # norm is w=0 there, w=1 for the plain convention
        fill = 0.0 if cfg.norm_offset else 1.0
        return jnp.full(shape, fill, dtype=cfg.dtype)

    def dense_init(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, dtype=jnp.float32) * fan_in**-0.5
        ).astype(cfg.dtype)

    ks = jax.random.split(k_layers, 7)
    layers = {
        "attn_norm": norm_init((L, h)),
        "wq": dense_init(ks[0], (L, h, cfg.q_dim), h),
        "wk": dense_init(ks[1], (L, h, cfg.kv_dim), h),
        "wv": dense_init(ks[2], (L, h, cfg.kv_dim), h),
        "wo": dense_init(ks[3], (L, cfg.q_dim, h), cfg.q_dim),
        "mlp_norm": norm_init((L, h)),
        "w_gate": dense_init(ks[4], (L, h, cfg.intermediate_size), h),
        "w_up": dense_init(ks[5], (L, h, cfg.intermediate_size), h),
        "w_down": dense_init(ks[6], (L, cfg.intermediate_size, h), cfg.intermediate_size),
    }
    if cfg.post_norms:
        layers["post_attn_norm"] = norm_init((L, h))
        layers["post_ffn_norm"] = norm_init((L, h))
    if cfg.qk_norm:
        layers["q_norm"] = norm_init((L, cfg.head_dim))
        layers["k_norm"] = norm_init((L, cfg.head_dim))
    if cfg.attn_bias:
        layers["bq"] = jnp.zeros((L, cfg.q_dim), dtype=cfg.dtype)
        layers["bk"] = jnp.zeros((L, cfg.kv_dim), dtype=cfg.dtype)
        layers["bv"] = jnp.zeros((L, cfg.kv_dim), dtype=cfg.dtype)
    params = {
        "embed": dense_init(k_embed, (cfg.vocab_size, h), h),
        "layers": layers,
        "final_norm": norm_init((h,)),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_init(k_head, (h, cfg.vocab_size), h)
    return params


def param_logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Pytree of logical axis names matching `init_params`' structure."""
    layers = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
        "w_gate": ("layers", "embed", "mlp"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    if cfg.post_norms:
        layers["post_attn_norm"] = ("layers", "embed")
        layers["post_ffn_norm"] = ("layers", "embed")
    if cfg.qk_norm:
        layers["q_norm"] = ("layers", None)
        layers["k_norm"] = ("layers", None)
    if cfg.attn_bias:
        layers["bq"] = ("layers", "heads")
        layers["bk"] = ("layers", "kv_heads")
        layers["bv"] = ("layers", "kv_heads")
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# -- forward -----------------------------------------------------------------


def _norm(cfg: "LlamaConfig", x, w):
    return rms_norm(x, w, cfg.rms_eps, offset=cfg.norm_offset)


def _post(cfg: "LlamaConfig", lp, name: str, y):
    """Sandwich (post) norm on a block output, when the family has them."""
    if cfg.post_norms:
        return _norm(cfg, y, lp[name])
    return y


def _embed_tokens(cfg: "LlamaConfig", params, tokens):
    x = params["embed"][tokens].astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.hidden_size**0.5, cfg.dtype)
    return x


def _mlp(cfg, x, gate, up, down):
    g = qmat(x, gate)
    u = qmat(x, up)
    if cfg.hidden_activation == "gelu":
        a = jax.nn.gelu(g.astype(jnp.float32), approximate=True)
    else:
        a = jax.nn.silu(g.astype(jnp.float32))
    return qmat((a.astype(x.dtype) * u), down)


def _ffn(cfg: "LlamaConfig", lp, x):
    """Dense SwiGLU or routed MoE, by config family (models/moe.py)."""
    if getattr(cfg, "num_experts", 0) > 1:
        from .moe import moe_ffn

        return moe_ffn(cfg, lp, x)
    return _mlp(cfg, x, lp["w_gate"], lp["w_up"], lp["w_down"])


def _project_qkv(cfg: LlamaConfig, lp, x, positions, cos_tab, sin_tab):
    """x: [b, s, h] -> q [b,s,heads,hd], k/v [b,s,kvh,hd], roped."""
    b, s, _ = x.shape
    q, k, v = qmat(x, lp["wq"]), qmat(x, lp["wk"]), qmat(x, lp["wv"])
    if cfg.attn_bias:
        q = q + lp["bq"].astype(q.dtype)
        k = k + lp["bk"].astype(k.dtype)
        v = v + lp["bv"].astype(v.dtype)
    q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        # per-head RMSNorm before RoPE (gemma-3 convention)
        q = rms_norm(q, lp["q_norm"], cfg.rms_eps, offset=cfg.norm_offset)
        k = rms_norm(k, lp["k_norm"], cfg.rms_eps, offset=cfg.norm_offset)
    q = apply_rope(q, positions, cos_tab, sin_tab)
    k = apply_rope(k, positions, cos_tab, sin_tab)
    return q, k, v


def _scatter_prefill(pages, new, page_table, positions, valid, page_size):
    """Write prefill K or V [b,s,kvh,hd] into the page pool.

    Invalid (padding) positions scatter to an out-of-bounds page -> dropped.
    """
    b, s = positions.shape
    num_pages = pages.shape[0]
    page_of = positions // page_size  # [b, s] logical page per token
    slot_of = positions % page_size
    phys = jnp.take_along_axis(page_table, page_of, axis=1)  # [b, s]
    phys = jnp.where(valid, phys, num_pages)
    return pages.at[phys.reshape(-1), slot_of.reshape(-1)].set(
        new.reshape((b * s,) + new.shape[2:]), mode="drop"
    )


def _scatter_rows(pages, new, page_table, row_slot, positions, page_size):
    """Write a flat packed buffer's K or V [T, kvh, hd] into the page pool:
    token t goes to its OWN sequence's page (``page_table[row_slot[t]]``)
    at its own position. Padding rows (``row_slot < 0``) scatter to an
    out-of-bounds page -> dropped."""
    num_pages = pages.shape[0]
    page_of = positions // page_size  # [T] logical page per token
    slot_of = positions % page_size
    safe = jnp.clip(row_slot, 0, page_table.shape[0] - 1)
    phys = page_table[safe, page_of]  # [T]
    phys = jnp.where(row_slot >= 0, phys, num_pages)
    return pages.at[phys, slot_of].set(new, mode="drop")


def _scatter_decode(pages, new, page_table, positions, page_size):
    """Write one token's K or V [b,kvh,hd] at `positions` [b]."""
    page_of = positions // page_size
    slot_of = positions % page_size
    phys = jnp.take_along_axis(page_table, page_of[:, None], axis=1)[:, 0]
    return pages.at[phys, slot_of].set(new, mode="drop")


def prefill(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [b, s] int32, right-padded
    seq_lens: jnp.ndarray,  # [b] int32
    cache: Tuple[jnp.ndarray, jnp.ndarray],  # k/v pages [L, P, ps, kvh, hd]
    page_table: jnp.ndarray,  # [b, pages_per_seq] int32
):
    """Prefill a batch of prompts, writing KV into the paged cache.

    Returns (logits [b, s, vocab], new_cache). The caller reads logits at
    seq_lens-1 to sample the first generated token.
    """
    b, s = tokens.shape
    k_pages, v_pages = cache
    page_size = k_pages.shape[2]
    cos_tab, sin_tab = rope_table(
        cfg.max_seq_len, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    valid = positions < seq_lens[:, None]

    x = _embed_tokens(cfg, params, tokens)

    def layer(x, scanned):
        lp, kp, vp = scanned
        h = _norm(cfg, x, lp["attn_norm"])
        q, k, v = _project_qkv(cfg, lp, h, positions, cos_tab, sin_tab)
        kp = _scatter_prefill(kp, k, page_table, positions, valid, page_size)
        vp = _scatter_prefill(vp, v, page_table, positions, valid, page_size)
        attn = causal_prefill_attention(q, k, v, seq_lens, impl=cfg.attention_impl)
        x = x + _post(cfg, lp, "post_attn_norm", qmat(attn.reshape(b, s, cfg.q_dim), lp["wo"]))
        h = _norm(cfg, x, lp["mlp_norm"])
        x = x + _post(cfg, lp, "post_ffn_norm", _ffn(cfg, lp, h))
        return x, (kp, vp)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], k_pages, v_pages)
    )
    x = _norm(cfg, x, params["final_norm"])
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qmat(x, head).astype(jnp.float32)
    return logits, (new_k, new_v)


def prefill_continue(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [b, s] int32 suffix tokens, right-padded
    start: jnp.ndarray,  # [b] int32 — absolute position of tokens[:, 0]
    suffix_lens: jnp.ndarray,  # [b] int32 — valid suffix length per row
    cache: Tuple[jnp.ndarray, jnp.ndarray],
    page_table: jnp.ndarray,  # [b, pages_per_seq] int32
):
    """Prefill a prompt SUFFIX against a cache whose first `start` tokens
    are already present (the prefix-caching hit path,
    engine/prefix_cache.py). Scatters only the suffix's KV; attention runs
    over the paged cache so suffix queries see the shared prefix.

    Returns (logits [b, s, vocab], new_cache); the caller samples at
    suffix_lens-1.
    """
    from ..ops.attention import paged_suffix_attention

    b, s = tokens.shape
    k_pages, v_pages = cache
    page_size = k_pages.shape[2]
    cos_tab, sin_tab = rope_table(
        cfg.max_seq_len, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    positions = start[:, None] + jnp.broadcast_to(
        jnp.arange(s, dtype=jnp.int32), (b, s)
    )
    valid = jnp.arange(s, dtype=jnp.int32)[None, :] < suffix_lens[:, None]

    x = _embed_tokens(cfg, params, tokens)

    def layer(x, scanned):
        lp, kp, vp = scanned
        h = _norm(cfg, x, lp["attn_norm"])
        q, k, v = _project_qkv(cfg, lp, h, positions, cos_tab, sin_tab)
        kp = _scatter_prefill(kp, k, page_table, positions, valid, page_size)
        vp = _scatter_prefill(vp, v, page_table, positions, valid, page_size)
        attn = paged_suffix_attention(q, kp, vp, page_table, start)
        x = x + _post(cfg, lp, "post_attn_norm", qmat(attn.reshape(b, s, cfg.q_dim), lp["wo"]))
        h = _norm(cfg, x, lp["mlp_norm"])
        x = x + _post(cfg, lp, "post_ffn_norm", _ffn(cfg, lp, h))
        return x, (kp, vp)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], k_pages, v_pages)
    )
    x = _norm(cfg, x, params["final_norm"])
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qmat(x, head).astype(jnp.float32)
    return logits, (new_k, new_v)


def mixed_step(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [T] int32 — flat packed token buffer
    row_slot: jnp.ndarray,  # [T] int32 — page_table row per token; -1 = pad
    positions: jnp.ndarray,  # [T] int32 — absolute position per token
    cache: Tuple[jnp.ndarray, jnp.ndarray],
    page_table: jnp.ndarray,  # [rows, pages_per_seq] int32
    mesh=None,  # tp mesh: the pallas ragged impl runs under shard_map
):
    """One token-packed mixed-batch step: prefill segments, suffix
    continuations, and decode steps for MANY sequences in one forward
    over a flat ``[token_budget]`` buffer (the packed serving path,
    engine/engine.py). Each token's KV is scattered into its own
    sequence's pages first, then ragged paged attention masks every row
    to its own sequence at positions <= its own — causal prefill, suffix
    continuation, and decode are all the same mask.

    ``page_table`` arrives already sliced to the step's KV width — the
    mixed program slices the device-resident full-width table with a
    static ``kv_pages_bucket`` bound before calling here (bit-exact:
    the dropped entries were hard-masked exact zeros for every row).
    Under a sharded mesh the gather/scatter and einsums GSPMD-partition
    over the kv_heads/heads shards; the ragged op routes per
    ops/attention.py:resolve_ragged_impl — the pallas kernel runs under
    ``shard_map`` over ``mesh``'s tp axis, the XLA twin partitions
    without it.

    Returns (logits [T, vocab], new_cache); the caller gathers the rows
    that sample (each segment's last token / each decode row). Padding
    rows write nothing and produce garbage logits.
    """
    (T,) = tokens.shape
    k_pages, v_pages = cache
    page_size = k_pages.shape[2]
    cos_tab, sin_tab = rope_table(
        cfg.max_seq_len, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    x = _embed_tokens(cfg, params, tokens)  # [T, h]

    def layer(x, scanned):
        lp, kp, vp = scanned
        h = _norm(cfg, x, lp["attn_norm"])
        q, k, v = _project_qkv(
            cfg, lp, h[None], positions[None], cos_tab, sin_tab
        )
        q, k, v = q[0], k[0], v[0]  # [T, heads/kvh, hd]
        kp = _scatter_rows(kp, k, page_table, row_slot, positions, page_size)
        vp = _scatter_rows(vp, v, page_table, row_slot, positions, page_size)
        attn = ragged_paged_attention(
            q, kp, vp, page_table, row_slot, positions,
            impl=cfg.attention_impl, mesh=mesh,
        )
        x = x + _post(
            cfg, lp, "post_attn_norm",
            qmat(attn.reshape(T, cfg.q_dim), lp["wo"]),
        )
        h = _norm(cfg, x, lp["mlp_norm"])
        x = x + _post(cfg, lp, "post_ffn_norm", _ffn(cfg, lp, h))
        return x, (kp, vp)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], k_pages, v_pages)
    )
    x = _norm(cfg, x, params["final_norm"])
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qmat(x, head).astype(jnp.float32)
    return logits, (new_k, new_v)


def decode_step(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [b] int32 — the latest token per sequence
    positions: jnp.ndarray,  # [b] int32 — its position (seq_len - 1)
    cache: Tuple[jnp.ndarray, jnp.ndarray],
    page_table: jnp.ndarray,  # [b, pages_per_seq]
    active: "jnp.ndarray | None" = None,  # [b] bool; inactive rows write nothing
):
    """One decode step for the whole running batch.

    Returns (logits [b, vocab], new_cache).

    Two cache-write strategies, selected by ``cfg.attention_impl``:
      * ``reference`` — scatter each layer's new K/V into the pool *before*
        attending (2 scatters x num_layers; the baseline semantics).
      * ``grouped`` / ``pallas`` — the serving fast path: attention reads the
        pool for positions < pos and takes the new token's K/V inline, so all
        layers' writes defer to ONE scatter after the layer scan. On TPU each
        XLA pool scatter costs far more than the bytes it writes, so this is
        the difference between ~480 and ~1100 tok/s on one v5e chip.

    ``active`` masks rows of a frozen slot (budget exhausted mid-chunk): their
    K/V writes drop (scatter to the out-of-bounds page) so replayed steps
    can't corrupt the cache; their logits are garbage the caller ignores.
    """
    if cfg.attention_impl == "reference":
        return _decode_step_scatter_first(
            params, cfg, tokens, positions, cache, page_table, active
        )
    b = tokens.shape[0]
    k_pages, v_pages = cache
    page_size = k_pages.shape[2]
    num_pages = k_pages.shape[1]
    cos_tab, sin_tab = rope_table(
        cfg.max_seq_len, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    x = _embed_tokens(cfg, params, tokens)  # [b, h]

    def layer(x, scanned):
        lp, kp, vp = scanned
        h = _norm(cfg, x, lp["attn_norm"])
        q, k, v = _project_qkv(
            cfg, lp, h[:, None, :], positions[:, None], cos_tab, sin_tab
        )
        q, k, v = q[:, 0], k[:, 0], v[:, 0]  # [b, heads/kvh, hd]
        attn = paged_decode_attention_inline(
            q, kp, vp, k, v, page_table, positions, impl=cfg.attention_impl
        )
        x = x + _post(cfg, lp, "post_attn_norm", qmat(attn.reshape(b, cfg.q_dim), lp["wo"]))
        h = _norm(cfg, x, lp["mlp_norm"])
        x = x + _post(cfg, lp, "post_ffn_norm", _ffn(cfg, lp, h))
        return x, (k, v)

    x, (k_all, v_all) = jax.lax.scan(
        layer, x, (params["layers"], k_pages, v_pages)
    )
    # One scatter for all layers: k_all/v_all are [L, b, kvh, hd].
    L = k_all.shape[0]
    page_of = positions // page_size
    slot_of = positions % page_size
    phys = jnp.take_along_axis(page_table, page_of[:, None], axis=1)[:, 0]
    if active is not None:
        phys = jnp.where(active, phys, num_pages)  # drop inactive rows
    li = jnp.broadcast_to(jnp.arange(L)[:, None], (L, b)).reshape(-1)
    pi = jnp.broadcast_to(phys[None, :], (L, b)).reshape(-1)
    si = jnp.broadcast_to(slot_of[None, :], (L, b)).reshape(-1)
    flat = (L * b, cfg.num_kv_heads, cfg.head_dim)
    new_k = k_pages.at[li, pi, si].set(k_all.reshape(flat), mode="drop")
    new_v = v_pages.at[li, pi, si].set(v_all.reshape(flat), mode="drop")

    x = _norm(cfg, x, params["final_norm"])
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qmat(x, head).astype(jnp.float32)
    return logits, (new_k, new_v)


def _decode_step_scatter_first(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    cache: Tuple[jnp.ndarray, jnp.ndarray],
    page_table: jnp.ndarray,
    active: "jnp.ndarray | None" = None,
):
    """The baseline decode step: per-layer scatter-then-attend."""
    b = tokens.shape[0]
    k_pages, v_pages = cache
    page_size = k_pages.shape[2]
    num_pages = k_pages.shape[1]
    cos_tab, sin_tab = rope_table(
        cfg.max_seq_len, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    seq_lens = positions + 1
    table = page_table
    if active is not None:
        # Route inactive rows' writes to the out-of-bounds page (dropped);
        # masking the table also keeps their (ignored) reads harmless.
        table = jnp.where(active[:, None], page_table, num_pages)

    x = _embed_tokens(cfg, params, tokens)  # [b, h]

    def layer(x, scanned):
        lp, kp, vp = scanned
        h = _norm(cfg, x, lp["attn_norm"])
        q, k, v = _project_qkv(
            cfg, lp, h[:, None, :], positions[:, None], cos_tab, sin_tab
        )
        q, k, v = q[:, 0], k[:, 0], v[:, 0]  # [b, heads/kvh, hd]
        kp = _scatter_decode(kp, k, table, positions, page_size)
        vp = _scatter_decode(vp, v, table, positions, page_size)
        attn = paged_decode_attention(
            q, kp, vp, page_table, seq_lens, impl=cfg.attention_impl
        )
        x = x + _post(cfg, lp, "post_attn_norm", qmat(attn.reshape(b, cfg.q_dim), lp["wo"]))
        h = _norm(cfg, x, lp["mlp_norm"])
        x = x + _post(cfg, lp, "post_ffn_norm", _ffn(cfg, lp, h))
        return x, (kp, vp)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], k_pages, v_pages)
    )
    x = _norm(cfg, x, params["final_norm"])
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qmat(x, head).astype(jnp.float32)
    return logits, (new_k, new_v)
