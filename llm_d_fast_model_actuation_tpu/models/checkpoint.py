"""Checkpoint save/load for engine weights — the real cold-start path.

The reference's dominant cold-start cost is model download + load into HBM
(vLLM's weight loading; the launcher exists to hide exactly this). Here the
serving engine loads params from an Orbax checkpoint directory:

  * sharding-aware restore: each leaf is restored DIRECTLY into its target
    NamedSharding/device placement (no host-then-scatter double copy) —
    Orbax on TPU reads from disk into per-device buffers;
  * level-2 wake (`engine/sleep.py` L2_DISCARD) re-loads from the same
    checkpoint, so a discard-sleep's wake is a disk read, not a re-init;
  * `save_params` exists so deployments can seed checkpoints from any
    source (HF export scripts, trainers) in the exact pytree layout
    `llama.init_params` defines.

Format: one Orbax StandardCheckpoint under ``<dir>/params`` plus a
``config.json`` carrying the LlamaConfig fields it was written with, so a
mismatched ISC option string fails loudly at load time instead of silently
serving shape-mangled weights.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

import jax

from . import llama

CONFIG_FILE = "config.json"
PARAMS_DIR = "params"
#: per-leaf content digests (engine/chunk_store.py), written at save time
#: so a load gets every weight's identity WITHOUT hashing restored device
#: arrays — the "hash computed once" contract for the Orbax path
MANIFEST_FILE = "manifest.json"

#: LlamaConfig fields that must match between checkpoint and engine config
#: (dtype/attention_impl are runtime choices, not weight-layout facts).
_SHAPE_FIELDS = (
    "vocab_size",
    "hidden_size",
    "num_layers",
    "num_heads",
    "num_kv_heads",
    "head_dim",
    "intermediate_size",
    "tie_embeddings",
    "num_experts",  # MoE family: expert count is a weight-layout fact
    # Gemma-family knobs: they change the parameter SET (post/qk norms) or
    # the stored-weight semantics ((1+w) zero-centered norms, GeGLU,
    # scaled embeddings) — a mismatch must fail loudly, not serve garbage
    "norm_offset",
    "hidden_activation",
    "embed_scale",
    "post_norms",
    "qk_norm",
)


def _config_dict(cfg: llama.LlamaConfig) -> Dict[str, Any]:
    d = dataclasses.asdict(cfg)
    d["dtype"] = str(cfg.dtype.__name__ if hasattr(cfg.dtype, "__name__") else cfg.dtype)
    return d


def save_params(
    directory: str, cfg: llama.LlamaConfig, params: Dict[str, Any]
) -> None:
    """Write params + config to `directory` (created; must not already hold
    a checkpoint)."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(directory, PARAMS_DIR), params)
        ckptr.wait_until_finished()
    with open(os.path.join(directory, CONFIG_FILE), "w") as f:
        json.dump(_config_dict(cfg), f, indent=2, sort_keys=True)
    # Content manifest (offline, so load pays nothing): flat key -> digest
    # over host copies of exactly what was written. Orbax restore is
    # bit-exact, so these identify the restored leaves too — the tiered
    # pool dedupes sibling fine-tune checkpoints and the delta-swap moves
    # only differing leaves on the strength of this file.
    from ..engine.chunk_store import digest_tree

    # digest_tree hashes leaf by leaf (leaf_digest np.asarray's each one),
    # so peak extra host memory is one leaf's copy, never a second full
    # model tree
    with open(os.path.join(directory, MANIFEST_FILE), "w") as f:
        json.dump({"format": 1, "digests": digest_tree(params)}, f, indent=2)


def validate_config(directory: str, cfg: llama.LlamaConfig) -> None:
    path = os.path.join(directory, CONFIG_FILE)
    try:
        with open(path) as f:
            saved = json.load(f)
    except OSError as e:
        raise FileNotFoundError(f"no checkpoint config at {path}") from e
    # A key absent from an older checkpoint's config.json means the
    # checkpoint predates the field: its weights carry the field's
    # then-implicit DEFAULT semantics, so compare against the dataclass
    # default — not the engine's value, which would accept any engine
    # setting and silently serve weights under the wrong convention.
    field_defaults = {
        f.name: f.default for f in dataclasses.fields(type(cfg))
    }
    mismatches = {
        k: (saved.get(k, field_defaults.get(k)), getattr(cfg, k, None))
        for k in _SHAPE_FIELDS
        if saved.get(k, field_defaults.get(k)) != getattr(cfg, k, None)
    }
    if mismatches:
        raise ValueError(
            f"checkpoint {directory} was written for a different model shape: "
            + ", ".join(
                f"{k}: ckpt={a} engine={b}" for k, (a, b) in mismatches.items()
            )
        )


def load_params(
    directory: str,
    cfg: llama.LlamaConfig,
    mesh: Optional[jax.sharding.Mesh] = None,
    stats_out: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Restore params from `directory`, directly into their serving
    placement (sharded over `mesh` when given, committed to the default
    device otherwise). Checkpoints are always the bf16 form: a quantized
    serving config restores bf16 and quantizes on the way in (runtime
    quantization, models/quant.py).

    ``stats_out`` (a dict, filled in place) records ``restore_s`` (the
    disk->device restore wall — Orbax lands each leaf straight in its
    placement, so read and H2D are one window) and ``bytes`` — the
    cold-load accounting the engine's swap metrics report on pool
    misses."""
    import time

    import orbax.checkpoint as ocp

    serve_cfg = cfg
    if getattr(cfg, "quantization", ""):
        import dataclasses

        cfg = dataclasses.replace(cfg, quantization="")

    validate_config(directory, cfg)
    directory = os.path.abspath(directory)

    # Build the target pytree abstractly: shapes/dtypes from init logic
    # without materializing weights (eval_shape), shardings from the same
    # logical-axis rules the engine serves with.
    from .registry import init_params_for

    abstract = jax.eval_shape(
        lambda: init_params_for(jax.random.key(0), cfg)
    )
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import named_sharding

        from .registry import logical_axes_for

        axes = logical_axes_for(cfg)

        def to_target(a, ax):
            sh = NamedSharding(mesh, P()) if ax is None else named_sharding(mesh, ax)
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)

        target = jax.tree.map(
            to_target,
            abstract,
            axes,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )
    else:
        sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        target = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sharding),
            abstract,
        )
    t0 = time.monotonic()
    with ocp.StandardCheckpointer() as ckptr:
        params = ckptr.restore(os.path.join(directory, PARAMS_DIR), target)
    if stats_out is not None:
        stats_out["restore_s"] = time.monotonic() - t0
        stats_out["bytes"] = sum(
            x.nbytes for x in jax.tree.leaves(params)
        )
        # content manifest written at save time (flat key -> digest):
        # the restored tree's identity without hashing device arrays;
        # a checkpoint predating the manifest just yields no digests
        mpath = os.path.join(directory, MANIFEST_FILE)
        if os.path.isfile(mpath):
            try:
                with open(mpath) as f:
                    stats_out["digests"] = json.load(f).get("digests") or {}
            except (OSError, ValueError):
                stats_out["digests"] = {}
    if serve_cfg is not cfg:
        from .registry import logical_axes_for, maybe_quantize

        params = maybe_quantize(serve_cfg, params)
        if mesh is not None:
            # re-pin: the eager quantize ops don't all preserve the serving
            # sharding (scale reductions in particular)
            from ..parallel.mesh import shard_pytree

            params = shard_pytree(params, mesh, logical_axes_for(serve_cfg))
    return params


def main(argv=None) -> int:
    """Seed a checkpoint directory (`python -m ...models.checkpoint --model
    bench-1b --out /ckpts/bench-1b`): random-init weights in the serving
    layout — deployments replace this with converted real weights."""
    import argparse
    import time

    p = argparse.ArgumentParser(prog="fma-seed-checkpoint")
    p.add_argument("--model", required=True, help="MODEL_CONFIGS key")
    p.add_argument("--out", required=True)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    from ..engine.server import MODEL_CONFIGS

    cfg = MODEL_CONFIGS[args.model]()
    t0 = time.monotonic()
    params = llama.init_params(jax.random.key(args.seed), cfg)
    params = jax.block_until_ready(params)
    save_params(args.out, cfg, params)
    nbytes = sum(x.nbytes for x in jax.tree.leaves(params))
    print(
        f"wrote {args.model} ({nbytes / 2**30:.2f} GiB) to {args.out} "
        f"in {time.monotonic() - t0:.1f}s"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
