"""Training step (next-token LM loss) for the model zoo.

The serving framework's main job is inference actuation, but the full
training step exists for two reasons: (a) the multi-chip dry-run contract
compiles it over a real dp/sp/tp mesh, exercising every sharding the engine
uses plus gradient collectives; (b) it makes the model zoo usable for
fine-tune-then-serve loops.

All control flow is compiler-friendly: one `lax.scan` over layers, masked
loss (no dynamic shapes), optional `jax.checkpoint` on the layer body to
trade FLOPs for HBM at long sequence lengths.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..ops.attention import causal_prefill_attention
from .llama import (  # noqa: F401
    LlamaConfig,
    _embed_tokens,
    _ffn,
    _norm,
    _post,
    _project_qkv,
    param_logical_axes,
)
from ..ops.rope import rope_table


def forward_train(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [b, s]
    seq_lens: jnp.ndarray,  # [b]
    remat: bool = True,
    mesh: Optional[Any] = None,
) -> jnp.ndarray:
    """Dense causal forward (no KV cache), logits fp32 [b, s, vocab].

    With a `mesh` whose ``sp`` axis is > 1, attention runs as RING attention
    (ops/ring_attention.py): K/V chunks rotate the sp ring instead of GSPMD
    all-gathering the whole sequence — the long-context path."""
    b, s = tokens.shape
    cos_tab, sin_tab = rope_table(
        cfg.max_seq_len, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    # the Gemma-family helpers keep training numerically identical to the
    # serving forward ((1+w) norms, sandwich norms, scaled embeddings)
    x = _embed_tokens(cfg, params, tokens)
    use_ring = mesh is not None and mesh.shape.get("sp", 1) > 1
    if use_ring:
        from ..ops.ring_attention import ring_prefill_attention

    def layer(x, lp):
        h = _norm(cfg, x, lp["attn_norm"])
        q, k, v = _project_qkv(cfg, lp, h, positions, cos_tab, sin_tab)
        if use_ring:
            attn = ring_prefill_attention(q, k, v, seq_lens, mesh)
        else:
            attn = causal_prefill_attention(q, k, v, seq_lens)
        x = x + _post(cfg, lp, "post_attn_norm", attn.reshape(b, s, cfg.q_dim) @ lp["wo"])
        h = _norm(cfg, x, lp["mlp_norm"])
        x = x + _post(cfg, lp, "post_ffn_norm", _ffn(cfg, lp, h))
        return x, None

    body = jax.checkpoint(layer) if remat else layer
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _norm(cfg, x, params["final_norm"])
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    return (x @ head).astype(jnp.float32)


def lm_loss(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jnp.ndarray,
    seq_lens: jnp.ndarray,
    mesh: Optional[Any] = None,
) -> jnp.ndarray:
    """Masked next-token cross-entropy."""
    logits = forward_train(params, cfg, tokens, seq_lens, mesh=mesh)
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    b, sm1 = targets.shape
    mask = (jnp.arange(sm1)[None, :] < (seq_lens - 1)[:, None]).astype(jnp.float32)
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


class TrainState(NamedTuple):
    step: jnp.ndarray
    params: Dict[str, Any]
    opt_state: Any


def make_optimizer(lr: float = 3e-4) -> optax.GradientTransformation:
    return optax.adamw(lr, weight_decay=0.01)


def make_train_state(
    params: Dict[str, Any], optimizer: Optional[optax.GradientTransformation] = None
) -> TrainState:
    optimizer = optimizer or make_optimizer()
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
    )


def train_step(
    state: TrainState,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,
    seq_lens: jnp.ndarray,
    optimizer: Optional[optax.GradientTransformation] = None,
    mesh: Optional[Any] = None,
) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
    """One optimizer step. Under a mesh, data arrays sharded (dp, sp) and
    params sharded per `param_logical_axes` make GSPMD insert the grad
    all-reduces — except attention under sp>1, which runs as explicit ring
    attention (pass `mesh`); no other hand-written collectives."""
    optimizer = optimizer or make_optimizer()
    loss, grads = jax.value_and_grad(lm_loss)(
        state.params, cfg, tokens, seq_lens, mesh
    )
    updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return (
        TrainState(step=state.step + 1, params=params, opt_state=opt_state),
        {"loss": loss},
    )
