"""Model families (functional JAX: params are pytrees, forward is pure).

The reference contains no model code (vLLM owns it); here the engine stratum
is in-repo, so the model zoo lives here. Each family exposes:
  * a config dataclass with known-size constructors,
  * ``init_params(key, cfg)`` -> bf16 pytree,
  * ``param_logical_axes(cfg)`` -> matching pytree of logical axis tuples
    (consumed by ``parallel.mesh.shard_pytree``),
  * ``prefill(...)`` / ``decode_step(...)`` pure functions built for
    ``lax.scan`` over layers and paged-KV caches.
"""

from .llama import (  # noqa: F401
    LlamaConfig,
    decode_step,
    init_params,
    param_logical_axes,
    prefill,
)
