"""In-process fakes for the dual-pods control plane.

Plays the roles the reference's kind-based e2e rig plays with containers
(SURVEY.md §4.3): a fake scheduler (chip assignment), fake launcher fleet
(protocol-faithful instance CRUDL), and fake engines (sleep/wake/health),
all behind the same Transports seam the production HTTP clients implement.
Used by the test suite AND by the benchmark harness's simulated mode
(reference: inference_server/benchmark/benchmark_base.py:34-99, mode
"simulated"); `SimLatencies` injects realistic delays so simulated scenario
timings are meaningful.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .api import constants as C
from .controller.clients import InstanceNotFound
from .controller.dualpods import (
    DualPodsConfig,
    DualPodsController,
)
from .controller.store import InMemoryStore


@dataclass
class SimLatencies:
    """Injected delays (seconds) so simulated-mode benchmark timings are
    meaningful; zeros (the default) keep unit tests instant."""

    launcher_start_s: float = 0.0  # launcher pod created -> ready
    instance_create_s: float = 0.0  # engine process cold start
    wake_s: float = 0.0  # level-1 wake (host -> HBM)
    sleep_s: float = 0.0  # level-1 sleep (HBM -> host)
    #: Running total of injected (scaled) delay — lets the benchmark unscale
    #: only the simulated-hardware share of a measurement instead of
    #: amplifying fixed harness overhead by 1/time_scale. Global counter:
    #: attribution to one actuation is only valid while actuations run
    #: serially (which the shipped scenarios do).
    injected_total_s: float = 0.0

    async def delay(self, seconds: float) -> None:
        if seconds > 0:
            self.injected_total_s += seconds
            await asyncio.sleep(seconds)


class FakeEngine:
    def __init__(self) -> None:
        self.sleeping = False
        self.healthy = True
        self.sleep_calls = 0
        self.wake_calls = 0


@dataclass
class FakeInstance:
    instance_id: str
    config: Dict[str, Any]
    status: str = "running"
    engine: FakeEngine = field(default_factory=FakeEngine)

    def state(self) -> Dict[str, Any]:
        return {
            "instance_id": self.instance_id,
            "status": self.status,
            **{k: v for k, v in self.config.items()},
        }


class FakeLauncher:
    def __init__(self, name: str, latencies: Optional[SimLatencies] = None) -> None:
        self.name = name
        self.latencies = latencies or SimLatencies()
        self.instances: Dict[str, FakeInstance] = {}
        self.created: List[str] = []
        self.deleted: List[str] = []

    async def create_named_instance(self, instance_id, config):
        if instance_id in self.instances:
            raise RuntimeError("409 duplicate")
        await self.latencies.delay(self.latencies.instance_create_s)
        inst = FakeInstance(instance_id, dict(config))
        self.instances[instance_id] = inst
        self.created.append(instance_id)
        return inst.state()

    async def list_instances(self):
        states = [i.state() for i in self.instances.values()]
        return {
            "total_instances": len(states),
            "running_instances": sum(1 for s in states if s["status"] == "running"),
            "instances": states,
        }

    async def get_instance(self, instance_id):
        if instance_id not in self.instances:
            raise InstanceNotFound(instance_id)
        return self.instances[instance_id].state()

    async def delete_instance(self, instance_id):
        if instance_id not in self.instances:
            raise InstanceNotFound(instance_id)
        inst = self.instances.pop(instance_id)
        self.deleted.append(instance_id)
        inst.status = "terminated"
        return inst.state()

    async def health(self):
        return True


class FakeSpi:
    def __init__(self, chips: List[str]) -> None:
        self.chips = chips
        self.ready = False
        self.memory: Dict[str, int] = {}

    async def accelerators(self):
        return list(self.chips)

    async def accelerator_memory(self):
        return dict(self.memory)

    async def become_ready(self):
        self.ready = True

    async def become_unready(self):
        self.ready = False


class FakeEngineHandle:
    def __init__(self, launcher: FakeLauncher, port: int) -> None:
        self._launcher = launcher
        self._port = port
        self._latencies = launcher.latencies

    def _target(self) -> Optional[FakeInstance]:
        for inst in self._launcher.instances.values():
            ann = inst.config.get("annotations") or {}
            if ann.get("inference-port") == str(self._port):
                return inst
        return None

    async def is_sleeping(self) -> bool:
        inst = self._target()
        if inst is None:
            raise RuntimeError(f"no instance on port {self._port}")
        return inst.engine.sleeping

    async def sleep(self, level: int = 1) -> None:
        inst = self._target()
        if inst is None:
            raise RuntimeError(f"no instance on port {self._port}")
        await self._latencies.delay(self._latencies.sleep_s)
        inst.engine.sleeping = True
        inst.engine.sleep_calls += 1

    async def wake_up(self) -> None:
        inst = self._target()
        if inst is None:
            raise RuntimeError(f"no instance on port {self._port}")
        await self._latencies.delay(self._latencies.wake_s)
        inst.engine.sleeping = False
        inst.engine.wake_calls += 1

    async def healthy(self) -> bool:
        inst = self._target()
        return inst is not None and inst.engine.healthy and not inst.engine.sleeping


class DirectEngineHandle:
    """Admin handle for a direct provider's (single) engine."""

    def __init__(self, engine: FakeEngine) -> None:
        self._e = engine

    async def is_sleeping(self) -> bool:
        return self._e.sleeping

    async def sleep(self, level: int = 1) -> None:
        self._e.sleeping = True
        self._e.sleep_calls += 1

    async def wake_up(self) -> None:
        self._e.sleeping = False
        self._e.wake_calls += 1

    async def healthy(self) -> bool:
        return self._e.healthy and not self._e.sleeping


class FakeTransports:
    def __init__(self, harness: "Harness") -> None:
        self._h = harness

    def launcher(self, pod):
        return self._h.launcher_for(pod["metadata"]["name"])

    def requester_spi(self, pod):
        return self._h.spi_for(pod["metadata"]["name"])

    def engine_admin(self, pod, port):
        from .controller.directpath import DIRECT_PROVIDER_COMPONENT

        labels = pod["metadata"].get("labels") or {}
        if labels.get(C.COMPONENT_LABEL) == DIRECT_PROVIDER_COMPONENT:
            return DirectEngineHandle(self._h.direct_engine_for(pod["metadata"]["name"]))
        return FakeEngineHandle(self._h.launcher_for(pod["metadata"]["name"]), port)


class Harness:
    def __init__(
        self,
        ns: str = "ns",
        latencies: Optional[SimLatencies] = None,
        store: Optional[Any] = None,
        **cfg_kwargs,
    ) -> None:
        self.ns = ns
        self.latencies = latencies or SimLatencies()
        self.store = store if store is not None else InMemoryStore()
        self.launchers: Dict[str, FakeLauncher] = {}
        self.spis: Dict[str, FakeSpi] = {}
        self.transports = FakeTransports(self)

        async def launcher_runtime(pod):
            self.launchers.setdefault(
                pod["metadata"]["name"],
                FakeLauncher(pod["metadata"]["name"], self.latencies),
            )
            await self.latencies.delay(self.latencies.launcher_start_s)
            # the "kubelet": give the pod an IP and mark it Ready
            def run(p):
                p.setdefault("status", {})["podIP"] = "10.0.0.1"
                p["status"]["conditions"] = [{"type": "Ready", "status": "True"}]
                return p

            self.store.mutate("Pod", pod["metadata"]["namespace"], pod["metadata"]["name"], run)

        self.direct_engines: Dict[str, FakeEngine] = {}

        async def provider_runtime(pod):
            # the "kubelet" for direct providers: engine comes up awake
            self.direct_engines.setdefault(pod["metadata"]["name"], FakeEngine())

            def run(p):
                p.setdefault("status", {})["podIP"] = "10.0.0.2"
                p["status"]["conditions"] = [{"type": "Ready", "status": "True"}]
                return p

            self.store.mutate("Pod", pod["metadata"]["namespace"], pod["metadata"]["name"], run)

        self.controller = DualPodsController(
            self.store,
            self.transports,
            DualPodsConfig(
                namespace=ns,
                launcher_runtime=launcher_runtime,
                provider_runtime=provider_runtime,
                **cfg_kwargs,
            ),
        )

    def launcher_for(self, name: str) -> FakeLauncher:
        if name not in self.launchers:
            self.launchers[name] = FakeLauncher(name, self.latencies)
        return self.launchers[name]

    def direct_engine_for(self, name: str) -> FakeEngine:
        if name not in self.direct_engines:
            self.direct_engines[name] = FakeEngine()
        return self.direct_engines[name]

    def spi_for(self, name: str) -> FakeSpi:
        if name not in self.spis:
            self.spis[name] = FakeSpi([])
        return self.spis[name]

    # -- object factories ----------------------------------------------------

    def add_isc(
        self,
        name: str,
        lc_name: str = "lc1",
        port: int = 8000,
        options: str = "--model tiny",
        labels: Optional[Dict[str, str]] = None,
        accelerator: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        return self.store.create(
            {
                "kind": "InferenceServerConfig",
                "metadata": {"name": name, "namespace": self.ns},
                "spec": {
                    "modelServerConfig": {
                        "port": port,
                        "options": options,
                        **({"labels": labels} if labels else {}),
                        **({"accelerator": accelerator} if accelerator else {}),
                    },
                    "launcherConfigName": lc_name,
                },
            }
        )

    def add_lc(self, name: str = "lc1", max_instances: int = 2) -> Dict[str, Any]:
        return self.store.create(
            {
                "kind": "LauncherConfig",
                "metadata": {"name": name, "namespace": self.ns},
                "spec": {
                    "podTemplate": {
                        "metadata": {},
                        "spec": {"containers": [{"name": "launcher"}]},
                    },
                    "maxInstances": max_instances,
                },
            }
        )

    def add_requester(
        self,
        name: str,
        isc_name: str,
        node: str = "n1",
        chips: Optional[List[str]] = None,
    ) -> Dict[str, Any]:
        self.spis[name] = FakeSpi(chips or ["chip-0"])
        return self.store.create(
            {
                "kind": "Pod",
                "metadata": {
                    "name": name,
                    "namespace": self.ns,
                    "annotations": {C.INFERENCE_SERVER_CONFIG_ANNOTATION: isc_name},
                },
                "spec": {
                    "nodeName": node,
                    "containers": [{"name": C.INFERENCE_SERVER_CONTAINER_NAME}],
                },
                "status": {
                    "podIP": "10.0.0.9",
                    "conditions": [{"type": "Ready", "status": "False"}],
                },
            }
        )

    def add_direct_requester(
        self,
        name: str,
        patch: str,
        node: str = "n1",
        chips: Optional[List[str]] = None,
        port: int = 8000,
    ) -> Dict[str, Any]:
        self.spis[name] = FakeSpi(chips or ["chip-0"])
        return self.store.create(
            {
                "kind": "Pod",
                "metadata": {
                    "name": name,
                    "namespace": self.ns,
                    "annotations": {C.SERVER_PATCH_ANNOTATION: patch},
                },
                "spec": {
                    "nodeName": node,
                    "containers": [
                        {
                            "name": C.INFERENCE_SERVER_CONTAINER_NAME,
                            "image": "requester-stub",
                            "readinessProbe": {"httpGet": {"port": port, "path": "/health"}},
                            "resources": {"limits": {C.TPU_RESOURCE: "1"}},
                        }
                    ],
                },
                "status": {
                    "podIP": "10.0.0.9",
                    "conditions": [{"type": "Ready", "status": "False"}],
                },
            }
        )

    def direct_provider_pods(self) -> List[Dict[str, Any]]:
        from .controller.directpath import DIRECT_PROVIDER_COMPONENT

        return self.store.list(
            "Pod", self.ns, selector={C.COMPONENT_LABEL: DIRECT_PROVIDER_COMPONENT}
        )

    # -- helpers -------------------------------------------------------------

    def launcher_pods(self) -> List[Dict[str, Any]]:
        return self.store.list(
            "Pod", self.ns, selector={C.COMPONENT_LABEL: C.LAUNCHER_COMPONENT}
        )

    def the_launcher_pod(self) -> Dict[str, Any]:
        pods = self.launcher_pods()
        assert len(pods) == 1, f"expected 1 launcher pod, got {len(pods)}"
        return pods[0]

    async def run(self, body) -> None:
        await self.controller.start()
        try:
            await body()
        finally:
            await self.controller.stop()

    async def settle(self, timeout: float = 20.0) -> None:
        await self.controller.quiesce(timeout)


def run_scenario(harness: Harness, body) -> None:
    asyncio.run(harness.run(body))
