"""Launcher CLI: preload, then serve the instance-management REST API.

Reference parity (launcher.py:900-967): ``--mock-gpus`` family becomes
``--mock-chips``; the launcher imports JAX + the engine modules *before* any
fork so children inherit warm modules, and it exports a persistent XLA
compilation-cache directory shared by every instance (on TPU, compilation —
not weight loading — dominates cold start; a shared cache turns repeat model
launches into cache hits).
"""

from __future__ import annotations

import argparse
import logging
import os

from aiohttp import web

logger = logging.getLogger(__name__)


def preload(compile_cache_dir: str) -> None:
    """Import the heavy modules once, pre-fork, and arm the persistent
    compilation cache (the TPU analogue of the reference's 'launcher imported
    vLLM before forking', launcher.py:836-885)."""
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", compile_cache_dir)
    os.makedirs(compile_cache_dir, exist_ok=True)
    import jax  # noqa: F401

    try:
        jax.config.update("jax_compilation_cache_dir", compile_cache_dir)
    except Exception:
        pass
    from ..engine import server as _server  # noqa: F401  (engine modules warm)
    from ..models import llama as _llama  # noqa: F401

    logger.info("preloaded jax %s; compile cache at %s", jax.__version__, compile_cache_dir)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="fma-tpu-launcher")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8001)
    p.add_argument("--log-level", default="info")
    p.add_argument("--mock-chips", action="store_true")
    p.add_argument("--mock-chip-count", type=int, default=8)
    p.add_argument("--mock-topology", default="")
    p.add_argument("--chip-map-path", default="")
    p.add_argument("--log-dir", default="")
    p.add_argument(
        "--compile-cache-dir", default="/tmp/fma-tpu-xla-cache"
    )
    p.add_argument("--no-preload", action="store_true")
    args = p.parse_args(argv)

    logging.basicConfig(level=getattr(logging, args.log_level.upper(), logging.INFO))
    if not args.no_preload:
        preload(args.compile_cache_dir)

    from .chiptranslator import ChipTranslator
    from .manager import EngineProcessManager
    from .rest import build_app

    translator = ChipTranslator.create(
        mock_chips=args.mock_chips,
        mock_chip_count=args.mock_chip_count,
        mock_topology=args.mock_topology,
        chip_map_path=args.chip_map_path or None,
    )
    manager = EngineProcessManager(translator, log_dir=args.log_dir)
    app = build_app(manager)
    logger.info(
        "launcher serving on %s:%s (%s chips, mode %s)",
        args.host,
        args.port,
        len(translator.chip_ids()),
        translator.mode,
    )
    web.run_app(app, host=args.host, port=args.port, print=None)


if __name__ == "__main__":
    main()
