"""Launcher CLI: preload, then serve the instance-management REST API.

Reference parity (launcher.py:900-967): ``--mock-gpus`` family becomes
``--mock-chips``; the launcher imports JAX + the engine modules *before* any
fork so children inherit warm modules, and it exports a persistent XLA
compilation-cache directory shared by every instance (on TPU, compilation —
not weight loading — dominates cold start; a shared cache turns repeat model
launches into cache hits).
"""

from __future__ import annotations

import argparse
import logging
import os

from aiohttp import web

logger = logging.getLogger(__name__)


def preload(compile_cache_dir: str) -> None:
    """Import the heavy modules once, pre-fork, and arm the persistent
    compilation cache (the TPU analogue of the reference's 'launcher imported
    vLLM before forking', launcher.py:836-885)."""
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", compile_cache_dir)
    # Serialized-executable spill for the engine's AOT pool rides next to
    # the XLA cache (engine/exec_pool.py): every child of this launcher
    # shares the directory, so a pooled executable survives instance
    # restarts and even seeds sibling instances of the same model.
    os.environ.setdefault(
        "FMA_EXEC_SPILL_DIR", os.path.join(compile_cache_dir, "exec-pool")
    )
    os.makedirs(compile_cache_dir, exist_ok=True)
    import jax  # noqa: F401

    try:
        jax.config.update("jax_compilation_cache_dir", compile_cache_dir)
    except Exception:
        pass
    from ..engine import server as _server  # noqa: F401  (engine modules warm)
    from ..models import llama as _llama  # noqa: F401

    logger.info("preloaded jax %s; compile cache at %s", jax.__version__, compile_cache_dir)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="fma-tpu-launcher")
    p.add_argument("--host", default="0.0.0.0")
    # FMA_LAUNCHER_PORT: the dual-pods controller injects this when a
    # hostNetwork node already has a launcher on the default port (same-node
    # port collision; the per-pod launcher-port annotation carries the same
    # value for the controller's transport)
    p.add_argument(
        "--port",
        type=int,
        default=int(os.environ.get("FMA_LAUNCHER_PORT", "8001")),
    )
    p.add_argument("--log-level", default="info")
    p.add_argument("--mock-chips", action="store_true")
    p.add_argument("--mock-chip-count", type=int, default=8)
    p.add_argument("--mock-topology", default="")
    p.add_argument("--chip-map-path", default="")
    p.add_argument("--log-dir", default="")
    p.add_argument(
        "--compile-cache-dir", default="/tmp/fma-tpu-xla-cache"
    )
    p.add_argument("--no-preload", action="store_true")
    # Crash supervision (launcher/manager.py RestartPolicy): 0 keeps the
    # pre-existing report-only behavior (controller re-pair heals crashes).
    p.add_argument(
        "--restart-budget",
        type=int,
        default=int(os.environ.get("FMA_RESTART_BUDGET", "0")),
        help="supervised restarts per crash loop for a crashed engine "
        "child (0 = report-only); a child that stays up past the reset "
        "window earns its budget back",
    )
    p.add_argument(
        "--restart-backoff",
        type=float,
        default=0.5,
        help="first restart delay (s); doubles per attempt with jitter",
    )
    p.add_argument(
        "--restart-backoff-max",
        type=float,
        default=30.0,
        help="backoff ceiling (s) for supervised restarts",
    )
    p.add_argument(
        "--restart-reset-window",
        type=float,
        default=300.0,
        help="uptime (s) after which a restarted child's crash counter "
        "resets (budget bounds crash loops, not lifetime restarts)",
    )
    p.add_argument(
        "--notify-pod",
        action="store_true",
        help="run the state-change reflector in-process (instead of the "
        "notifier sidecar): patch the launcher Pod's instance-signature "
        "annotation on every instance state change (needs POD_NAME/NAMESPACE)",
    )
    args = p.parse_args(argv)

    logging.basicConfig(level=getattr(logging, args.log_level.upper(), logging.INFO))
    if not args.no_preload:
        preload(args.compile_cache_dir)

    from ..utils import faults
    from .chiptranslator import ChipTranslator
    from .manager import EngineProcessManager, RestartPolicy
    from .rest import build_app

    # FMA_FAULTS armed pre-fork: launcher-process points (launcher.rpc,
    # instance.spawn) fire here; engine children re-load their own env
    faults.load_env()

    translator = ChipTranslator.create(
        mock_chips=args.mock_chips,
        mock_chip_count=args.mock_chip_count,
        mock_topology=args.mock_topology,
        chip_map_path=args.chip_map_path or None,
    )
    restart_policy = None
    if args.restart_budget > 0:
        restart_policy = RestartPolicy(
            budget=args.restart_budget,
            backoff_s=args.restart_backoff,
            backoff_max_s=args.restart_backoff_max,
            reset_window_s=args.restart_reset_window,
        )
    manager = EngineProcessManager(
        translator, log_dir=args.log_dir, restart_policy=restart_policy
    )
    app = build_app(manager)

    if args.notify_pod:
        import asyncio

        from .notifier import InstanceStateNotifier, kubectl_patcher

        pod_name = os.environ.get("POD_NAME", "")
        namespace = os.environ.get("NAMESPACE", "")
        if not pod_name or not namespace:
            p.error("--notify-pod needs POD_NAME and NAMESPACE env (Downward API)")

        async def lister():
            return manager.get_all_instances_status().get("instances", [])

        async def watcher(since_revision: int):
            # cursor = since_revision (tracked by the notifier), so events
            # published between connect and first read are replayed
            return manager.broadcaster.subscribe(since_revision=since_revision)

        notifier = InstanceStateNotifier(
            lister, kubectl_patcher(pod_name, namespace), watcher=watcher
        )

        async def start_notifier(app):
            app["notifier_task"] = asyncio.get_running_loop().create_task(
                notifier.run()
            )

        async def stop_notifier(app):
            notifier.stop()
            task = app["notifier_task"]
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

        app.on_startup.append(start_notifier)
        app.on_cleanup.append(stop_notifier)
    logger.info(
        "launcher serving on %s:%s (%s chips, mode %s)",
        args.host,
        args.port,
        len(translator.chip_ids()),
        translator.mode,
    )
    web.run_app(app, host=args.host, port=args.port, print=None)


if __name__ == "__main__":
    main()
