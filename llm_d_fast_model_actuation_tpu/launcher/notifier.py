"""State-change reflector: instance state -> launcher Pod annotation.

A crash inside a launcher is node-local; the controller watches the kube
API, not launcher internals. The reflector closes that gap by stamping a
signature of the launcher's instance set onto the launcher Pod's
`vllm-instance-signature` annotation — any instance-state change becomes a
Pod-update event the controller's informer sees (reference sidecar:
inference_server/launcher/launcher_pod_notifier.py:16-198).

TPU-first delta: the reference polls `/v2/vllm/instances` every 2 s. Here
the reflector consumes the launcher's revisioned NDJSON watch stream, so a
crash is reflected within one event round-trip with zero idle polling; a
broken stream degrades to periodic polling until the launcher returns.

Ordering invariant (no lost-update window): the watch stream is CONNECTED
before each list+patch, so every state change is either (a) already visible
to the list, or (b) delivered as an event after the connection — there is no
gap in which a change can slip through unreflected.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import random
import shutil
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, List, Optional

from ..api.constants import INSTANCE_SIGNATURE_ANNOTATION as SIGNATURE_ANNOTATION
from ..utils.events import RevisionTooOld

logger = logging.getLogger(__name__)

#: `watcher(since_revision)` -> a CONNECTED async iterator of watch events.
#: Connection (or revision-cursor capture) must be effective at return time.
WatcherFactory = Callable[[int], Awaitable[AsyncIterator[Any]]]


def instance_signature(states: List[Dict[str, Any]]) -> str:
    """SHA-256 over the sorted (instance_id, status) pairs
    (launcher_pod_notifier.py's signature, kept byte-compatible in spirit)."""
    pairs = sorted((s.get("instance_id", ""), s.get("status", "")) for s in states)
    return hashlib.sha256(json.dumps(pairs).encode()).hexdigest()


class InstanceStateNotifier:
    """Watches a launcher and patches the signature on change.

    `lister` returns the launcher's instance states; `watcher` (optional,
    see :data:`WatcherFactory`) yields watch events — used only as change
    triggers, the list is always the source of truth; `patch` applies the
    new signature to the launcher Pod (kube patch in production, a store
    mutate in tests).
    """

    def __init__(
        self,
        lister: Callable[[], Awaitable[List[Dict[str, Any]]]],
        patch: Callable[[str], Awaitable[None]],
        watcher: Optional[WatcherFactory] = None,
        poll_interval_s: float = 2.0,
        reconnect_backoff_s: float = 0.5,
        reconnect_backoff_max_s: float = 30.0,
    ) -> None:
        self._lister = lister
        self._patch = patch
        self._watcher = watcher
        self._poll_interval_s = poll_interval_s
        # Reconnect discipline: a down launcher must not be hammered on a
        # fixed cadence — consecutive connect/stream failures back off
        # exponentially (with jitter, so a fleet of sidecars doesn't
        # reconnect in lockstep) up to a capped ceiling, and one success
        # resets the schedule.
        self._reconnect_backoff_s = reconnect_backoff_s
        self._reconnect_backoff_max_s = reconnect_backoff_max_s
        self._consecutive_failures = 0
        self._last_signature: Optional[str] = None
        self._last_revision = 0
        self._stopping = False

    def _reconnect_delay(self) -> float:
        """Delay before the next watch (re)connect after N consecutive
        failures: min(cap, base * 2**(N-1)), jittered into [d/2, d] so a
        fleet of sidecars spreads out while the configured ceiling stays a
        hard cap."""
        n = max(1, self._consecutive_failures)
        d = min(
            self._reconnect_backoff_max_s,
            self._reconnect_backoff_s * (2 ** (n - 1)),
        )
        return d * (0.5 + 0.5 * random.random())

    async def reflect_once(self) -> Optional[str]:
        """List, compute, patch-if-changed. Returns the new signature when a
        patch was made, else None."""
        states = await self._lister()
        sig = instance_signature(states)
        if sig == self._last_signature:
            return None
        await self._patch(sig)
        self._last_signature = sig
        logger.info("instance signature -> %s (%d instances)", sig[:12], len(states))
        return sig

    async def run(self) -> None:
        """Event loop. Each cycle: connect the watch stream FIRST, then
        reflect (so nothing slips between list and subscribe), then reflect
        again on every event. Falls back to polling without a watcher."""
        while not self._stopping:
            stream: Optional[AsyncIterator[Any]] = None
            connect_failed = False
            if self._watcher is not None:
                try:
                    stream = await self._watcher(self._last_revision)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    if isinstance(e, RevisionTooOld):
                        # resume cursor evicted: restart from the buffer
                        # start; the reflect below covers current state
                        self._last_revision = 0
                    connect_failed = True
                    self._consecutive_failures += 1
                    logger.warning(
                        "watch connect failed (%s); retry %d backing off",
                        e, self._consecutive_failures,
                    )

            await self._reflect_guarded()

            if stream is None:
                # no watcher configured: steady polling cadence; a FAILED
                # connect instead backs off exponentially (capped, with
                # jitter) so a down launcher isn't hammered
                await asyncio.sleep(
                    self._reconnect_delay()
                    if connect_failed
                    else self._poll_interval_s
                )
                continue
            self._consecutive_failures = 0  # connected: schedule resets
            try:
                async for event in stream:
                    rev = (event.get("object") or {}).get("revision") if isinstance(
                        event, dict
                    ) else None
                    if isinstance(rev, int):
                        self._last_revision = max(self._last_revision, rev)
                    await self._reflect_guarded()
                    if self._stopping:
                        break
            except asyncio.CancelledError:
                raise
            except Exception as e:
                if isinstance(e, RevisionTooOld):
                    self._last_revision = 0
                self._consecutive_failures += 1
                logger.warning(
                    "watch stream broke (%s); resync %d backing off",
                    e, self._consecutive_failures,
                )
                await asyncio.sleep(self._reconnect_delay())

    async def _reflect_guarded(self) -> None:
        try:
            await self.reflect_once()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.warning("reflect failed: %s", e)

    def stop(self) -> None:
        self._stopping = True


# ---------------------------------------------------------------- sidecar glue


class HttpSource:
    """Launcher REST access for the sidecar: one shared ClientSession for the
    lifetime of the notifier (not one per call)."""

    def __init__(self, base_url: str) -> None:
        self._base = base_url.rstrip("/")
        self._session = None  # type: ignore[assignment]

    async def _ensure_session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=None, sock_read=None)
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def lister(self) -> List[Dict[str, Any]]:
        session = await self._ensure_session()
        async with session.get(f"{self._base}/v2/vllm/instances") as resp:
            resp.raise_for_status()
            body = await resp.json()
        return body.get("instances", [])

    async def watcher(self, since_revision: int) -> AsyncIterator[Any]:
        """Connect the watch stream before returning (see the notifier's
        ordering invariant). A 410 on a resume revision falls back to
        watching from now — the caller reflects right after we return, which
        covers everything up to this connection."""
        session = await self._ensure_session()
        url = f"{self._base}/v2/vllm/instances/watch"
        params = {"since": str(since_revision)} if since_revision > 0 else None
        resp = await session.get(url, params=params)
        if resp.status == 410:
            resp.release()
            resp = await session.get(url)
        resp.raise_for_status()

        async def gen() -> AsyncIterator[Any]:
            try:
                async for line in resp.content:
                    if line.strip():
                        yield json.loads(line)
            finally:
                resp.release()

        return gen()


def kubectl_patcher(pod_name: str, namespace: str):
    """Annotate the launcher Pod via kubectl (the sidecar has a service
    account; this avoids requiring a python kube client in the image)."""
    if shutil.which("kubectl") is None:
        raise RuntimeError("kubectl not found; provide a custom patcher")

    async def patch(signature: str) -> None:
        proc = await asyncio.create_subprocess_exec(
            "kubectl",
            "annotate",
            "pod",
            pod_name,
            "-n",
            namespace,
            f"{SIGNATURE_ANNOTATION}={signature}",
            "--overwrite",
            stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.PIPE,
        )
        _, err = await proc.communicate()
        if proc.returncode != 0:
            raise RuntimeError(f"kubectl annotate failed: {err.decode()[:500]}")

    return patch


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    import os

    parser = argparse.ArgumentParser(description="launcher state-change reflector")
    parser.add_argument("--launcher-url", default="http://127.0.0.1:8001")
    parser.add_argument("--pod-name", default=os.environ.get("POD_NAME", ""))
    parser.add_argument("--namespace", default=os.environ.get("NAMESPACE", ""))
    parser.add_argument("--poll-interval", type=float, default=2.0)
    args = parser.parse_args(argv)
    if not args.pod_name or not args.namespace:
        parser.error("--pod-name and --namespace (or POD_NAME/NAMESPACE env) required")

    source = HttpSource(args.launcher_url)
    notifier = InstanceStateNotifier(
        lister=source.lister,
        patch=kubectl_patcher(args.pod_name, args.namespace),
        watcher=source.watcher,
        poll_interval_s=args.poll_interval,
    )

    async def run() -> None:
        try:
            await notifier.run()
        finally:
            await source.close()

    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
