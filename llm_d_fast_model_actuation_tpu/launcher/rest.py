"""Launcher REST API (aiohttp).

Wire-compatible with the reference launcher's FastAPI surface
(launcher.py:568-800) so the reference's Go `launcherclient` drives this
launcher unchanged: same paths (`/v2/vllm/instances...`), same status codes
(201 create, 409 duplicate PUT, 404 missing, 410 stale watch revision, 206/416
ranged logs with Content-Range), same NDJSON watch event shape
``{"type": CREATED|STOPPED|DELETED, "object": {...}}``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
from http import HTTPStatus
from typing import Optional, Tuple

from aiohttp import web

from ..utils import tracing
from ..utils.events import RevisionTooOld
from .instance import InstanceConfig, InvalidInstanceConfig, LogRangeNotAvailable
from .manager import ChipConflict
from .manager import DrainFailed
from .manager import EngineProcessManager
from .manager import MigrateFailed
from .manager import PrefetchFailed
from .manager import ResidentsFailed
from .manager import SwapFailed

logger = logging.getLogger(__name__)

_RANGE_RE = re.compile(r"^bytes=(\d+)-(\d+)?$")


def parse_range_header(value: str) -> Tuple[int, Optional[int]]:
    """``bytes=START-END`` or ``bytes=START-`` (suffix ranges rejected)."""
    m = _RANGE_RE.match(value)
    if m is None:
        raise ValueError(f"Unsupported or malformed Range header: {value}")
    start = int(m.group(1))
    end = int(m.group(2)) if m.group(2) else None
    if end is not None and end < start:
        raise ValueError(f"Range end ({end}) must be >= start ({start})")
    return start, end


def build_app(manager: EngineProcessManager) -> web.Application:
    app = web.Application()
    app["manager"] = manager

    def _traced_call(request: web.Request, fn):
        """Run a blocking manager verb on the executor with the caller's
        ``traceparent`` (if any) as the current context — the launcher's
        create/swap spans then join the controller's actuation trace
        (docs/tracing.md), and the engine hop + fork env carry it on."""
        return tracing.run_traced(
            asyncio.get_running_loop(), request.headers, fn
        )

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "OK"})

    async def metrics(request: web.Request) -> web.Response:
        """Launcher-process prometheus exposition: the launcher RPC
        latency family (fma_launcher_rpc_seconds) lives in THIS process —
        without this route it would be registered but unscrapeable. The
        fleet rollup refreshes first (executor: it polls engine children
        over HTTP) so one scrape carries current fma_launcher_fleet_*
        aggregates."""
        from prometheus_client import generate_latest

        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(None, manager.fleet_rollup)
        except Exception:  # noqa: BLE001 — stale gauges beat a failed scrape
            logger.warning("fleet rollup during scrape failed", exc_info=True)
        return web.Response(
            body=generate_latest(), content_type="text/plain"
        )

    async def index(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "name": "Multi-Instance Engine Management API (TPU)",
                "version": "2.0",
                "endpoints": {
                    "index": "GET /",
                    "health": "GET /health",
                    "metrics": "GET /metrics",
                    "create_instance": "POST /v2/vllm/instances",
                    "create_named_instance": "PUT /v2/vllm/instances/{instance_id}",
                    "delete_instance": "DELETE /v2/vllm/instances/{instance_id}",
                    "delete_all_instances": "DELETE /v2/vllm/instances",
                    "get_instance_status": "GET /v2/vllm/instances/{instance_id}",
                    "get_all_instances": "GET /v2/vllm/instances",
                    "get_instance_logs": "GET /v2/vllm/instances/{instance_id}/log",
                    "swap_instance": "POST /v2/vllm/instances/{instance_id}/swap",
                    "prefetch_instance": "POST /v2/vllm/instances/{instance_id}/prefetch",
                    "prefetch_status": "GET /v2/vllm/instances/{instance_id}/prefetch",
                    "abort_prefetch": "DELETE /v2/vllm/instances/{instance_id}/prefetch",
                    "migrate_instance": "POST /v2/vllm/instances/{instance_id}/migrate",
                    "drain_instance": "POST /v2/vllm/instances/{instance_id}/drain",
                    "attach_resident": "POST /v2/vllm/instances/{instance_id}/residents",
                    "residents_status": "GET /v2/vllm/instances/{instance_id}/residents",
                    "detach_resident": "DELETE /v2/vllm/instances/{instance_id}/residents",
                    "watch_instances": "GET /v2/vllm/instances/watch",
                    "faults": "GET/POST/DELETE /v2/vllm/faults",
                    "traces": "GET /v2/vllm/traces",
                    "exemplars": "GET /v2/vllm/exemplars",
                },
            }
        )

    async def _parse_config(request: web.Request) -> InstanceConfig:
        try:
            body = await request.json()
            return InstanceConfig.from_dict(body)
        except (json.JSONDecodeError, ValueError, TypeError) as e:
            raise web.HTTPUnprocessableEntity(text=f"invalid instance config: {e}")

    async def create_instance(request: web.Request) -> web.Response:
        config = await _parse_config(request)
        try:
            # create forks + may probe overlapping engines over HTTP (2 s
            # timeout each) — keep the event loop free
            result = await _traced_call(
                request, lambda: manager.create_instance(config)
            )
        except InvalidInstanceConfig as e:
            raise web.HTTPUnprocessableEntity(text=str(e))
        except ChipConflict as e:
            raise web.HTTPConflict(text=str(e))
        except Exception as e:
            logger.exception("create failed")
            raise web.HTTPInternalServerError(text=str(e))
        _watch_sentinel(manager, result["instance_id"])
        return web.json_response(result, status=HTTPStatus.CREATED)

    async def create_named_instance(request: web.Request) -> web.Response:
        instance_id = request.match_info["instance_id"]
        config = await _parse_config(request)
        try:
            result = await _traced_call(
                request,
                lambda: manager.create_instance(config, instance_id=instance_id),
            )
        except InvalidInstanceConfig as e:
            raise web.HTTPUnprocessableEntity(text=str(e))
        except (ValueError, ChipConflict) as e:
            raise web.HTTPConflict(text=str(e))
        except Exception as e:
            logger.exception("create failed")
            raise web.HTTPInternalServerError(text=str(e))
        _watch_sentinel(manager, instance_id)
        return web.json_response(result, status=HTTPStatus.CREATED)

    async def delete_instance(request: web.Request) -> web.Response:
        instance_id = request.match_info["instance_id"]
        loop = asyncio.get_running_loop()
        inst = manager.instances.get(instance_id)
        if inst is not None:
            inst.cancel_sentinel_watcher()  # must run on the loop thread
        try:
            # stop() blocks on SIGTERM/join for seconds; keep the loop live.
            result = await loop.run_in_executor(
                None, manager.stop_instance, instance_id
            )
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        return web.json_response(result)

    async def delete_all(request: web.Request) -> web.Response:
        loop = asyncio.get_running_loop()
        for inst in list(manager.instances.values()):
            inst.cancel_sentinel_watcher()
        result = await loop.run_in_executor(None, manager.stop_all_instances)
        return web.json_response(result)

    async def get_all(request: web.Request) -> web.Response:
        detail = request.query.get("detail", "true").lower() != "false"
        if detail:
            # executor: the fleet block polls engine children over HTTP
            # (short per-child timeout); the loop must stay free
            return web.json_response(
                await asyncio.get_running_loop().run_in_executor(
                    None,
                    lambda: manager.get_all_instances_status(
                        include_fleet=True
                    ),
                )
            )
        ids = manager.list_instances()
        return web.json_response(
            {"revision": manager.revision, "instance_ids": ids, "count": len(ids)}
        )

    async def get_one(request: web.Request) -> web.Response:
        instance_id = request.match_info["instance_id"]
        try:
            return web.json_response(manager.get_instance_status(instance_id))
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")

    async def watch(request: web.Request) -> web.StreamResponse:
        since_raw = request.query.get("since")
        try:
            since = int(since_raw) if since_raw is not None else None
        except ValueError:
            raise web.HTTPBadRequest(text=f"invalid since revision: {since_raw!r}")
        if since is not None:
            oldest = manager.broadcaster.oldest_revision
            if oldest is not None and since < oldest - 1:
                raise web.HTTPGone(
                    text=f"Requested revision {since} is no longer available. "
                    f"Oldest available: {oldest}."
                )
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "application/x-ndjson",
                "X-Content-Type-Options": "nosniff",
            },
        )
        await resp.prepare(request)

        async def send(obj) -> None:
            await resp.write((json.dumps(obj) + "\n").encode())

        if since is None:
            start_revision = manager.revision
            for instance in list(manager.instances.values()):
                await send({"type": "CREATED", "object": instance.get_status()})
        else:
            start_revision = since
        try:
            async for event in manager.broadcaster.subscribe(start_revision):
                await send(event)
        except RevisionTooOld:
            pass
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        return resp

    async def swap_instance(request: web.Request) -> web.Response:
        """Model hot-swap verb: rebind a live instance to a different model
        over the engine child's /v1/swap — same chip set, same process, no
        stop/start cycle (docs/engine.md "Model hot-swap")."""
        instance_id = request.match_info["instance_id"]
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        model = body.get("model")
        if not isinstance(model, str) or not model:
            raise web.HTTPUnprocessableEntity(
                text="swap requires a 'model' string"
            )
        checkpoint_dir = body.get("checkpoint_dir") or ""
        if not isinstance(checkpoint_dir, str):
            raise web.HTTPUnprocessableEntity(
                text="checkpoint_dir must be a string"
            )
        try:
            # the swap streams model state for seconds; keep the loop free
            result = await _traced_call(
                request,
                lambda: manager.swap_instance(
                    instance_id, model, checkpoint_dir=checkpoint_dir
                ),
            )
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        except SwapFailed as e:
            # engine-side rejection (bad model name, gang, sleeping) maps
            # to the client's fault; a rolled-back swap is a retryable 503;
            # a timed-out-and-unrecovered swap is 504; an unreachable child
            # is a gateway error
            if 400 <= e.status < 500:
                raise web.HTTPBadRequest(text=str(e))
            if e.status == 503:
                raise web.HTTPServiceUnavailable(text=str(e))
            if e.status == 504:
                raise web.HTTPGatewayTimeout(text=str(e))
            raise web.HTTPBadGateway(text=str(e))
        return web.json_response(result)

    def _map_prefetch_error(e: PrefetchFailed):
        # engine-side rejection (bad model, gang, already running) is the
        # client's fault; a timed-out child is 504, unreachable is 502
        if 400 <= e.status < 500:
            return web.HTTPBadRequest(text=str(e))
        if e.status == 504:
            return web.HTTPGatewayTimeout(text=str(e))
        return web.HTTPBadGateway(text=str(e))

    async def prefetch_instance(request: web.Request) -> web.Response:
        """Background-prefetch verb: stage a model's weights host-resident
        on a live instance (engine POST /v1/prefetch) while it keeps
        serving — the controller's hint for the predicted next swap."""
        instance_id = request.match_info["instance_id"]
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        model = body.get("model")
        if not isinstance(model, str) or not model:
            raise web.HTTPUnprocessableEntity(
                text="prefetch requires a 'model' string"
            )
        checkpoint_dir = body.get("checkpoint_dir") or ""
        if not isinstance(checkpoint_dir, str):
            raise web.HTTPUnprocessableEntity(
                text="checkpoint_dir must be a string"
            )
        try:
            result = await _traced_call(
                request,
                lambda: manager.prefetch_instance(
                    instance_id, model, checkpoint_dir=checkpoint_dir
                ),
            )
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        except PrefetchFailed as e:
            raise _map_prefetch_error(e)
        return web.json_response(result)

    async def get_instance_prefetch(request: web.Request) -> web.Response:
        instance_id = request.match_info["instance_id"]
        try:
            result = await asyncio.get_running_loop().run_in_executor(
                None, lambda: manager.get_instance_prefetch(instance_id)
            )
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        except PrefetchFailed as e:
            raise _map_prefetch_error(e)
        return web.json_response(result)

    async def abort_instance_prefetch(request: web.Request) -> web.Response:
        instance_id = request.match_info["instance_id"]
        try:
            result = await asyncio.get_running_loop().run_in_executor(
                None, lambda: manager.abort_instance_prefetch(instance_id)
            )
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        except PrefetchFailed as e:
            raise _map_prefetch_error(e)
        return web.json_response(result)

    def _map_migrate_error(e):
        # the engines' 409 is an explicit precondition refusal (identity
        # mismatch, residents attached, spent fence, no capacity / drain
        # not converging) with nothing displaced — preserved verbatim so
        # an orchestrator can react to exactly that signal; 404 is a bad
        # destination id; 504 timed out (recovery already ran on the
        # engines); anything else is a gateway/engine failure
        if e.status == 409:
            return web.HTTPConflict(text=str(e))
        if e.status == 404:
            return web.HTTPNotFound(text=str(e))
        if 400 <= e.status < 500:
            return web.HTTPBadRequest(text=str(e))
        if e.status == 504:
            return web.HTTPGatewayTimeout(text=str(e))
        return web.HTTPBadGateway(text=str(e))

    async def migrate_instance(request: web.Request) -> web.Response:
        """Live-migration verb: hand the instance's in-flight and queued
        requests to a sibling serving the same model — transactional,
        fenced, streams keep flowing (docs/operations.md "Draining a
        node without dropping streams"). Body: optional ``dest_id`` to
        pin the destination (default: first eligible sibling)."""
        instance_id = request.match_info["instance_id"]
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                raise web.HTTPBadRequest(text="invalid JSON body")
        else:
            body = {}
        dest_id = body.get("dest_id")
        if dest_id is not None and (
            not isinstance(dest_id, str) or not dest_id
        ):
            raise web.HTTPUnprocessableEntity(
                text="dest_id must be a non-empty string"
            )
        try:
            # export + import move KV bytes for seconds; keep the loop free
            result = await _traced_call(
                request,
                lambda: manager.migrate_instance(instance_id, dest_id=dest_id),
            )
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        except MigrateFailed as e:
            raise _map_migrate_error(e)
        return web.json_response(result)

    async def drain_instance(request: web.Request) -> web.Response:
        """Node-drain verb: repeat migrate passes until the instance
        reports no live work, leaving it idle and safe to kill while
        every displaced stream keeps flowing through the source's
        proxies."""
        instance_id = request.match_info["instance_id"]
        try:
            result = await _traced_call(
                request, lambda: manager.drain_instance(instance_id)
            )
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        except (DrainFailed, MigrateFailed) as e:
            raise _map_migrate_error(e)
        return web.json_response(result)

    def _map_residents_error(e: ResidentsFailed):
        # the engine's 409 is the explicit admission rejection (cap / HBM
        # budget / detach-while-live) — preserved verbatim so a scheduler
        # can fall back to the swap path on exactly that signal
        if e.status == 409:
            return web.HTTPConflict(text=str(e))
        if 400 <= e.status < 500:
            return web.HTTPBadRequest(text=str(e))
        if e.status == 504:
            return web.HTTPGatewayTimeout(text=str(e))
        return web.HTTPBadGateway(text=str(e))

    async def _residents_write(
        request: web.Request, verb
    ) -> web.Response:
        """Shared body/validation for the attach/detach resident verbs
        (engine POST/DELETE /v1/residents; docs/launcher.md)."""
        instance_id = request.match_info["instance_id"]
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        model = body.get("model")
        if not isinstance(model, str) or not model:
            raise web.HTTPUnprocessableEntity(
                text="residents requires a 'model' string"
            )
        checkpoint_dir = body.get("checkpoint_dir") or ""
        if not isinstance(checkpoint_dir, str):
            raise web.HTTPUnprocessableEntity(
                text="checkpoint_dir must be a string"
            )
        try:
            result = await _traced_call(
                request,
                lambda: verb(
                    instance_id, model, checkpoint_dir=checkpoint_dir
                ),
            )
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        except ResidentsFailed as e:
            raise _map_residents_error(e)
        return web.json_response(result)

    async def attach_instance_resident(
        request: web.Request,
    ) -> web.Response:
        """Co-residency attach verb: device-resident sibling variant next
        to the instance's base (engine POST /v1/residents) — route
        per-request afterwards, zero actuation per request."""
        return await _residents_write(
            request, manager.attach_instance_resident
        )

    async def detach_instance_resident(
        request: web.Request,
    ) -> web.Response:
        return await _residents_write(
            request, manager.detach_instance_resident
        )

    async def get_instance_residents(
        request: web.Request,
    ) -> web.Response:
        instance_id = request.match_info["instance_id"]
        try:
            result = await asyncio.get_running_loop().run_in_executor(
                None, lambda: manager.get_instance_residents(instance_id)
            )
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        except ResidentsFailed as e:
            raise _map_residents_error(e)
        return web.json_response(result)

    async def get_log(request: web.Request) -> web.Response:
        instance_id = request.match_info["instance_id"]
        range_header = request.headers.get("Range")
        if range_header is None:
            start, end, partial = 0, None, False
        else:
            try:
                start, end = parse_range_header(range_header)
            except ValueError as e:
                raise web.HTTPBadRequest(text=str(e))
            partial = True
        try:
            data, total = manager.get_instance_log_bytes(instance_id, start, end)
        except KeyError:
            raise web.HTTPNotFound(text=f"Instance {instance_id} not found")
        except LogRangeNotAvailable as e:
            if not partial:
                # Rangeless GET of a still-empty log is a healthy 200, not 416.
                return web.Response(
                    body=b"",
                    status=HTTPStatus.OK,
                    content_type="application/octet-stream",
                    headers={"Accept-Ranges": "bytes"},
                )
            return web.Response(
                body=b"",
                status=HTTPStatus.REQUESTED_RANGE_NOT_SATISFIABLE,
                content_type="application/octet-stream",
                headers={"Content-Range": f"bytes */{e.total}"},
            )
        actual_end = start + len(data) - 1
        return web.Response(
            body=data,
            status=HTTPStatus.PARTIAL_CONTENT if partial else HTTPStatus.OK,
            content_type="application/octet-stream",
            headers={
                "Accept-Ranges": "bytes",
                "Content-Range": f"bytes {start}-{actual_end}/{total}",
            },
        )

    async def faults_get(request: web.Request) -> web.Response:
        from ..utils import faults

        return web.json_response(faults.describe())

    async def faults_arm(request: web.Request) -> web.Response:
        """Arm launcher-process fault points (launcher.rpc,
        instance.spawn) for tests and fault drills (utils/faults.py)."""
        from ..utils import faults

        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        spec = body.get("spec")
        if not isinstance(spec, str) or not spec:
            # 400 like the engine's mirrored /v1/faults — one convention
            # for drill scripts hitting either surface
            raise web.HTTPBadRequest(text="faults requires a 'spec' string")
        try:
            faults.arm_spec(spec)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response(faults.describe())

    async def faults_reset(request: web.Request) -> web.Response:
        from ..utils import faults

        faults.reset()
        return web.json_response(faults.describe())

    async def exemplars(request: web.Request) -> web.Response:
        """GET /v2/vllm/exemplars: the fleet's SLO-violation exemplars —
        last-N violated requests across every reporting child, each with
        its trace_id, leg-duration breakdown, and owning instance, so an
        operator can jump straight from "attainment is dropping" to one
        child's GET /v1/traces?trace_id= (docs/operations.md)."""
        try:
            fleet = await asyncio.get_running_loop().run_in_executor(
                None, manager.fleet_rollup
            )
        except Exception as e:  # noqa: BLE001 — degraded poll, not a 500
            logger.warning("fleet rollup for exemplars failed", exc_info=True)
            raise web.HTTPServiceUnavailable(text=str(e))
        return web.json_response(
            {
                "slo_exemplars": fleet.get("slo_exemplars") or [],
                "slo_attainment": fleet.get("slo_attainment"),
                "slo_requests_violated": fleet.get(
                    "slo_requests_violated", 0
                ),
            }
        )

    async def traces(request: web.Request) -> web.Response:
        """Export the LAUNCHER process's span ring buffer (create/swap/
        restart verbs + launcher.rpc hops). The engine children export
        their own via GET /v1/traces; together the per-process Chrome
        JSONs merge into one Perfetto timeline (docs/tracing.md)."""
        status, body, ctype = tracing.export_http(
            request.query.get("format", "chrome"),
            trace_id=request.query.get("trace_id") or None,
            clear=request.query.get("clear") in ("1", "true"),
        )
        return web.Response(status=status, text=body, content_type=ctype)

    app.router.add_get("/health", health)
    app.router.add_get("/v2/vllm/traces", traces)
    app.router.add_get("/v2/vllm/exemplars", exemplars)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/", index)
    app.router.add_get("/v2/vllm/faults", faults_get)
    app.router.add_post("/v2/vllm/faults", faults_arm)
    app.router.add_delete("/v2/vllm/faults", faults_reset)
    app.router.add_get("/v2/vllm/instances/watch", watch)
    app.router.add_post("/v2/vllm/instances", create_instance)
    app.router.add_put("/v2/vllm/instances/{instance_id}", create_named_instance)
    app.router.add_delete("/v2/vllm/instances/{instance_id}", delete_instance)
    app.router.add_delete("/v2/vllm/instances", delete_all)
    app.router.add_get("/v2/vllm/instances", get_all)
    app.router.add_get("/v2/vllm/instances/{instance_id}", get_one)
    app.router.add_get("/v2/vllm/instances/{instance_id}/log", get_log)
    app.router.add_post("/v2/vllm/instances/{instance_id}/swap", swap_instance)
    app.router.add_post(
        "/v2/vllm/instances/{instance_id}/prefetch", prefetch_instance
    )
    app.router.add_get(
        "/v2/vllm/instances/{instance_id}/prefetch", get_instance_prefetch
    )
    app.router.add_delete(
        "/v2/vllm/instances/{instance_id}/prefetch", abort_instance_prefetch
    )
    app.router.add_post(
        "/v2/vllm/instances/{instance_id}/migrate", migrate_instance
    )
    app.router.add_post(
        "/v2/vllm/instances/{instance_id}/drain", drain_instance
    )
    app.router.add_post(
        "/v2/vllm/instances/{instance_id}/residents",
        attach_instance_resident,
    )
    app.router.add_get(
        "/v2/vllm/instances/{instance_id}/residents",
        get_instance_residents,
    )
    app.router.add_delete(
        "/v2/vllm/instances/{instance_id}/residents",
        detach_instance_resident,
    )

    async def on_shutdown(app: web.Application) -> None:
        manager.stop_all_instances()

    app.on_shutdown.append(on_shutdown)
    return app


def _watch_sentinel(manager: EngineProcessManager, instance_id: str) -> None:
    """Arm crash detection for a just-created instance (needs a running
    event loop, hence done in the handler, not the manager)."""
    instance = manager.instances.get(instance_id)
    if instance is not None:
        try:
            instance.start_sentinel_watcher(manager._on_instance_stopped)
        except RuntimeError:
            logger.warning("no running loop; sentinel watcher not armed")
