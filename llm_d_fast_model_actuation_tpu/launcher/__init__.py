"""The launcher: node-local engine-instance manager with a REST API.

TPU edition of the reference's `inference_server/launcher/`: it preloads the
expensive modules (JAX, libtpu bindings, the engine) once, then forks engine
instances on demand so cold start skips interpreter+import time; it owns a
persistent XLA compilation-cache dir shared by all instances; it detects
instance crashes with zero polling via process-sentinel fds; and it speaks
the same REST surface as the reference launcher (`/v2/vllm/instances` CRUDL,
NDJSON watch with revisions + 410 resync, RFC 9110 ranged log reads) so the
reference's controllers can drive it unchanged.

TPU-specific: chip identity is topology-aware (`ChipTranslator`), sleeping
instances must *release their chips* before another instance can open them —
chip-set ownership is serialized per launcher (`ChipLedger`).
"""

from .chiptranslator import ChipTranslator  # noqa: F401
from .instance import EngineInstance, HalfMade  # noqa: F401
from .manager import EngineProcessManager  # noqa: F401
