"""Multi-instance manager: CRUDL over engine instances + revisioned events.

Mirrors the reference's `VllmMultiProcessManager` (launcher.py:344-515): a
monotonically increasing revision counter stamped on every lifecycle event
(CREATED / STOPPED / DELETED), duplicate-ID create is an error (REST maps it
to 409), stop is graceful-then-kill, and a crashed child produces a STOPPED
event with its exit code via the sentinel watcher.

TPU delta: a `ChipLedger` records which chip sets are held by live instance
processes, and the manager *enforces* it: on TPU a chip has exactly one
process-holder at a time (a second PJRT client blocks in init), so creating
an instance whose chips overlap an AWAKE holder can only wedge — the
launcher refuses with 409. Overlap with holders that are all ASLEEP (devices
released; see engine/sleep.py) is the product's time-sharing path and is
allowed. The dual-pods controller remains the party that orchestrates who
sleeps when; the ledger is the node-local safety net against a controller
bug silently double-booking a chip.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
import uuid as uuidlib
from typing import Any, Callable, Dict, List, Optional

from ..utils.events import EventBroadcaster
from .chiptranslator import ChipTranslator
from .instance import EngineInstance, InstanceConfig

logger = logging.getLogger(__name__)

STATUS_STOPPED = "stopped"
STATUS_RUNNING = "running"


class ChipConflict(Exception):
    """Requested chips overlap an instance that is (or may be) awake."""

    def __init__(self, instance_id: str, blockers: List[str]) -> None:
        super().__init__(
            f"instance {instance_id}: chips held by awake (or not-yet-probeable) "
            f"instance(s) {blockers}; a TPU chip has one holder — sleep them first"
        )
        self.instance_id = instance_id
        self.blockers = blockers


class SwapFailed(Exception):
    """The engine child rejected (or never answered) a model hot-swap."""

    def __init__(self, instance_id: str, status: int, detail: str) -> None:
        super().__init__(
            f"swap of instance {instance_id} failed ({status}): {detail}"
        )
        self.instance_id = instance_id
        self.status = status
        self.detail = detail


class PrefetchFailed(Exception):
    """The engine child rejected (or never answered) a prefetch verb."""

    def __init__(self, instance_id: str, status: int, detail: str) -> None:
        super().__init__(
            f"prefetch on instance {instance_id} failed ({status}): {detail}"
        )
        self.instance_id = instance_id
        self.status = status
        self.detail = detail


def probe_instance_awake(instance: "EngineInstance") -> Optional[bool]:
    """Ask the instance's engine admin API whether it still holds its chips.

    Returns True ("awake": serving, or sleeping with the TPU client still
    open — either way the chip is held), False (asleep AND devices released
    — the chip is genuinely free), or None (engine not reachable — still
    booting, crashed, or a test fake)."""
    try:
        from ..engine.server import parse_engine_options

        port = parse_engine_options(instance.config.options).port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/is_sleeping", timeout=2
        ) as resp:
            body = json.loads(resp.read() or b"{}")
        return not (
            body.get("is_sleeping", False)
            and body.get("devices_released", False)
        )
    except Exception:
        return None


class ChipLedger:
    """Node-local truth of which live instance holds which chips (and which
    model each holder currently serves — hot-swap rebinds the model without
    touching the chip set, so the holder entry survives swaps unchanged)."""

    def __init__(self) -> None:
        self._held: Dict[str, List[str]] = {}  # instance_id -> chip_ids
        self._models: Dict[str, str] = {}  # instance_id -> served model
        #: instance_id -> model hinted/staged via the prefetch verb: the
        #: controller's "predicted next model" for this holder. Cleared
        #: when the hint is consumed (swap to that model), aborted, or
        #: the holder releases its chips.
        self._prefetched: Dict[str, str] = {}

    def overlapping(
        self, chip_ids: Optional[List[str]], exclude: Optional[str] = None
    ) -> List[str]:
        """Instance IDs whose recorded chip sets overlap `chip_ids`."""
        chips = set(chip_ids or [])
        return [
            iid
            for iid, held in self._held.items()
            if iid != exclude and chips & set(held)
        ]

    def acquire(self, instance_id: str, chip_ids: Optional[List[str]]) -> List[str]:
        """Record ownership; returns the list of instance IDs whose chip sets
        overlap (empty = clean placement)."""
        overlaps = self.overlapping(chip_ids, exclude=instance_id)
        self._held[instance_id] = sorted(set(chip_ids or []))
        return overlaps

    def release(self, instance_id: str) -> None:
        self._held.pop(instance_id, None)
        self._models.pop(instance_id, None)
        self._prefetched.pop(instance_id, None)

    def set_model(self, instance_id: str, model: str) -> None:
        """Record which model a holder serves (updated on hot-swap). A
        swap to the prefetched model consumes the prefetch hint."""
        if instance_id in self._held:
            self._models[instance_id] = model
            if self._prefetched.get(instance_id) == model:
                self._prefetched.pop(instance_id, None)

    def set_prefetched(self, instance_id: str, model: Optional[str]) -> None:
        """Record (or with None, clear) the model a holder has staged via
        the prefetch verb."""
        if model is None:
            self._prefetched.pop(instance_id, None)
        elif instance_id in self._held:
            self._prefetched[instance_id] = model

    def holders(self) -> Dict[str, List[str]]:
        return dict(self._held)

    def models(self) -> Dict[str, str]:
        return dict(self._models)

    def prefetched(self) -> Dict[str, str]:
        return dict(self._prefetched)


class EngineProcessManager:
    def __init__(
        self,
        translator: ChipTranslator,
        log_dir: str = "",
        kickoff=None,
        enforce_chip_exclusivity: bool = True,
        awake_probe: Optional[
            Callable[["EngineInstance"], Optional[bool]]
        ] = None,
    ) -> None:
        self.instances: Dict[str, EngineInstance] = {}
        self.translator = translator
        if log_dir:
            import os

            os.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        self.ledger = ChipLedger()
        self.broadcaster = EventBroadcaster()
        self._revision = 0
        # create/sentinel publish on the loop thread; stop_instance publishes
        # from the REST handler's executor thread — revision minting and the
        # buffer append must be one atomic step or a watcher can skip events
        self._rev_lock = threading.Lock()
        self._kickoff = kickoff
        # With a fake kickoff there is no engine admin API to probe, so the
        # sleep state of an overlapping holder is unknowable — enforcement
        # stays opt-in for such managers (tests pass a probe or disable).
        self.enforce_chip_exclusivity = enforce_chip_exclusivity
        self._awake_probe = awake_probe or probe_instance_awake

    # -- revisions -----------------------------------------------------------

    @property
    def revision(self) -> int:
        return self._revision

    def _next_revision(self) -> int:
        self._revision += 1
        return self._revision

    def _publish(self, event_type: str, obj: Dict[str, Any]) -> int:
        """Mint-and-append atomically (cross-thread safe); returns the
        revision stamped on the event."""
        with self._rev_lock:
            rev = self._next_revision()
            obj["revision"] = rev
            self.broadcaster.publish_nowait(rev, {"type": event_type, "object": obj})
        return rev

    # -- CRUDL ---------------------------------------------------------------

    def create_instance(
        self, config: InstanceConfig, instance_id: Optional[str] = None
    ) -> Dict[str, Any]:
        iid = instance_id or str(uuidlib.uuid4())
        if iid in self.instances:
            raise ValueError(f"instance {iid} already exists")
        if self._kickoff is None:
            # Real engine path: validate the options string pre-fork so a bad
            # config is a 422 at create time, not a crash discovered later.
            from ..engine.server import parse_engine_options
            from .instance import InvalidInstanceConfig

            try:
                parse_engine_options(config.options)
            except Exception as e:
                raise InvalidInstanceConfig(f"invalid engine options: {e}")
        overlaps = self.ledger.overlapping(config.chip_ids, exclude=iid)
        if overlaps and self.enforce_chip_exclusivity:
            # Allowed only if EVERY overlapping holder is verifiably asleep
            # with devices released. Unreachable == possibly booting ==
            # treated awake: refusing a race beats wedging the chip.
            blockers = []
            for other in overlaps:
                inst = self.instances.get(other)
                if inst is None:
                    # stale ledger entry (a failed create); drop, not block
                    self.ledger.release(other)
                    continue
                if self._awake_probe(inst) is not False:
                    blockers.append(other)
            if blockers:
                raise ChipConflict(iid, blockers)
        elif overlaps:
            logger.warning(
                "instance %s chips overlap live instances %s "
                "(enforcement off: controller must ensure they are asleep)",
                iid,
                overlaps,
            )
        kwargs = {} if self._kickoff is None else {"kickoff": self._kickoff}
        instance = EngineInstance(
            iid, config, self.translator, log_dir=self.log_dir, **kwargs
        )
        result = instance.start()
        # record ownership only once the process actually exists — a failed
        # start must not leak a chips hold
        self.ledger.acquire(iid, config.chip_ids)
        try:
            from ..engine.server import parse_engine_options

            self.ledger.set_model(
                iid, parse_engine_options(config.options).model
            )
        except Exception:  # noqa: BLE001 — fake-kickoff tests use free-form options
            pass
        self.instances[iid] = instance
        published = dict(result)
        instance.last_revision = self._publish("CREATED", published)
        result["revision"] = instance.last_revision
        logger.info("created instance %s (rev %s)", iid, instance.last_revision)
        return result

    def _on_instance_stopped(self, instance_id: str, exitcode) -> None:
        """Sentinel callback: the child died on its own."""
        instance = self.instances.get(instance_id)
        if instance is None:
            return
        self.ledger.release(instance_id)
        obj = instance.get_status()
        obj["exit_code"] = exitcode
        instance.last_revision = self._publish("STOPPED", obj)
        logger.warning(
            "instance %s stopped itself (exit code %s)", instance_id, exitcode
        )

    def stop_instance(self, instance_id: str, timeout: float = 10) -> Dict[str, Any]:
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        instance = self.instances[instance_id]
        instance.cancel_sentinel_watcher()
        result = instance.stop(timeout=timeout)
        del self.instances[instance_id]
        self.ledger.release(instance_id)
        published = dict(result)
        result["revision"] = self._publish("DELETED", published)
        logger.info("stopped instance %s", instance_id)
        return result

    def swap_instance(
        self,
        instance_id: str,
        model: str,
        checkpoint_dir: str = "",
        timeout: float = 300,
    ) -> Dict[str, Any]:
        """Hot-swap the model a live instance serves: forward to the engine
        child's POST /v1/swap (no stop/start cycle — the chip set, the
        process, and its ChipLedger hold are all unchanged), then bring the
        stored config and ledger in line with the model actually served."""
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        instance = self.instances[instance_id]
        from ..engine.server import parse_engine_options

        try:
            previous = parse_engine_options(instance.config.options).model
        except Exception:
            previous = ""
        body = self._engine_request(
            instance_id, "POST", "/v1/swap",
            {"model": model, "checkpoint_dir": checkpoint_dir},
            timeout, SwapFailed,
        )
        from .instance import replace_model_option

        # rewrite from the ENGINE's answer, not the request: a pool hit
        # restores the pooled runtime's own checkpoint identity, and the
        # stored options must describe what the child actually serves
        # (a restart rebuilds from them)
        instance.config.options = replace_model_option(
            instance.config.options,
            model,
            checkpoint_dir=body.get("checkpoint_dir") or checkpoint_dir,
        )
        self.ledger.set_model(instance_id, model)
        obj = instance.get_status()
        obj["swap"] = body
        instance.last_revision = self._publish("SWAPPED", obj)
        logger.info(
            "swapped instance %s: %s -> %s (pool_hit=%s, rev %s)",
            instance_id, previous, model, body.get("pool_hit"),
            instance.last_revision,
        )
        return {
            "instance_id": instance_id,
            "model": model,
            "previous_model": previous,
            "swap": body,
            "revision": instance.last_revision,
        }

    def _engine_request(
        self,
        instance_id: str,
        method: str,
        api_path: str,
        body: Optional[Dict[str, Any]],
        timeout: float,
        exc_cls,
    ) -> Dict[str, Any]:
        """Forward an admin verb to a live instance's engine child; maps
        stored-options/HTTP failures onto `exc_cls(instance_id, status,
        detail)` the REST layer turns into 4xx/502."""
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        instance = self.instances[instance_id]
        from ..engine.server import parse_engine_options

        try:
            opts = parse_engine_options(instance.config.options)
        except Exception as e:
            # free-form options are tolerated at create time (fake-kickoff
            # managers); admin verbs on such an instance are a client error
            raise exc_cls(
                instance_id, 400,
                f"stored options are not engine options: {e}",
            )
        req = urllib.request.Request(
            f"http://127.0.0.1:{opts.port}{api_path}",
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            raise exc_cls(instance_id, e.code, detail)
        except Exception as e:  # noqa: BLE001 — unreachable child, timeout, ...
            raise exc_cls(instance_id, 502, f"engine unreachable: {e}")

    def prefetch_instance(
        self,
        instance_id: str,
        model: str,
        checkpoint_dir: str = "",
        timeout: float = 60,
    ) -> Dict[str, Any]:
        """Background-prefetch verb: have a live instance stage `model`'s
        weights host-resident (engine POST /v1/prefetch) while it keeps
        serving its current model, and record the hint in the ChipLedger —
        the dual-pods controller's way of warming the predicted next swap
        without touching the chip set or the serving process."""
        body = self._engine_request(
            instance_id, "POST", "/v1/prefetch",
            {"model": model, "checkpoint_dir": checkpoint_dir},
            timeout, PrefetchFailed,
        )
        # The hint is ADVISORY: it is recorded when the engine accepts the
        # staging and the background outcome is reconciled on status reads
        # (get_instance_prefetch drops it on failed/rejected/aborted) — a
        # controller that acts on the hint without having polled may still
        # get a cold build if the staging later failed.
        self.ledger.set_prefetched(instance_id, model)
        logger.info(
            "prefetch on instance %s: %s (state=%s)",
            instance_id, model, body.get("state"),
        )
        return {
            "instance_id": instance_id,
            "model": model,
            "prefetch": body,
        }

    def abort_instance_prefetch(
        self, instance_id: str, timeout: float = 90
    ) -> Dict[str, Any]:
        """Cancel an instance's in-flight prefetch (engine DELETE
        /v1/prefetch) and drop the ledger hint."""
        body = self._engine_request(
            instance_id, "DELETE", "/v1/prefetch", None, timeout,
            PrefetchFailed,
        )
        # keep the hint when there was nothing to abort because the
        # prefetch already COMPLETED: the staged weights are still pooled
        # and a swap to them is still warm — the hint is still true
        if body.get("aborted") or body.get("state") != "completed":
            self.ledger.set_prefetched(instance_id, None)
        return {
            "instance_id": instance_id,
            "prefetch": body,
        }

    def get_instance_prefetch(
        self, instance_id: str, timeout: float = 10
    ) -> Dict[str, Any]:
        """Prefetch status passthrough (engine GET /v1/prefetch). Also
        reconciles the advisory ledger hint: a staging that ended
        failed/rejected/aborted is no longer a warm next model."""
        body = self._engine_request(
            instance_id, "GET", "/v1/prefetch", None, timeout, PrefetchFailed
        )
        if body.get("state") in ("failed", "rejected", "aborted"):
            self.ledger.set_prefetched(instance_id, None)
        return {"instance_id": instance_id, "prefetch": body}

    def stop_all_instances(self, timeout: float = 10) -> Dict[str, Any]:
        stopped = []
        for iid in list(self.instances):
            self.stop_instance(iid, timeout=timeout)
            stopped.append(iid)
        return {"status": "all_stopped", "stopped_instances": stopped}

    def get_instance_status(self, instance_id: str) -> Dict[str, Any]:
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        return self.instances[instance_id].get_status()

    def get_all_instances_status(self) -> Dict[str, Any]:
        statuses = []
        running = 0
        for instance in self.instances.values():
            st = instance.get_status()
            statuses.append(st)
            if st["status"] == STATUS_RUNNING:
                running += 1
        return {
            "total_instances": len(statuses),
            "running_instances": running,
            "instances": statuses,
        }

    def list_instances(self) -> List[str]:
        return list(self.instances.keys())

    def get_instance_log_bytes(
        self, instance_id: str, start: int = 0, end: Optional[int] = None
    ):
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        return self.instances[instance_id].get_log_bytes(start, end)
