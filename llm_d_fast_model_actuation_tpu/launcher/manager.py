"""Multi-instance manager: CRUDL over engine instances + revisioned events.

Mirrors the reference's `VllmMultiProcessManager` (launcher.py:344-515): a
monotonically increasing revision counter stamped on every lifecycle event
(CREATED / STOPPED / DELETED), duplicate-ID create is an error (REST maps it
to 409), stop is graceful-then-kill, and a crashed child produces a STOPPED
event with its exit code via the sentinel watcher.

TPU delta: a `ChipLedger` records which chip sets are held by live instance
processes; overlapping placements are reported (the dual-pods controller is
the one that guarantees at most one *awake* instance per chip set — the
ledger gives it the node-local truth to verify against).
"""

from __future__ import annotations

import logging
import threading
import uuid as uuidlib
from typing import Any, Dict, List, Optional

from ..utils.events import EventBroadcaster
from .chiptranslator import ChipTranslator
from .instance import EngineInstance, InstanceConfig

logger = logging.getLogger(__name__)

STATUS_STOPPED = "stopped"
STATUS_RUNNING = "running"


class ChipLedger:
    """Node-local truth of which live instance holds which chips."""

    def __init__(self) -> None:
        self._held: Dict[str, List[str]] = {}  # instance_id -> chip_ids

    def acquire(self, instance_id: str, chip_ids: Optional[List[str]]) -> List[str]:
        """Record ownership; returns the list of instance IDs whose chip sets
        overlap (empty = clean placement)."""
        chips = set(chip_ids or [])
        overlaps = [
            iid
            for iid, held in self._held.items()
            if iid != instance_id and chips & set(held)
        ]
        self._held[instance_id] = sorted(chips)
        return overlaps

    def release(self, instance_id: str) -> None:
        self._held.pop(instance_id, None)

    def holders(self) -> Dict[str, List[str]]:
        return dict(self._held)


class EngineProcessManager:
    def __init__(
        self,
        translator: ChipTranslator,
        log_dir: str = "",
        kickoff=None,
    ) -> None:
        self.instances: Dict[str, EngineInstance] = {}
        self.translator = translator
        if log_dir:
            import os

            os.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        self.ledger = ChipLedger()
        self.broadcaster = EventBroadcaster()
        self._revision = 0
        # create/sentinel publish on the loop thread; stop_instance publishes
        # from the REST handler's executor thread — revision minting and the
        # buffer append must be one atomic step or a watcher can skip events
        self._rev_lock = threading.Lock()
        self._kickoff = kickoff

    # -- revisions -----------------------------------------------------------

    @property
    def revision(self) -> int:
        return self._revision

    def _next_revision(self) -> int:
        self._revision += 1
        return self._revision

    def _publish(self, event_type: str, obj: Dict[str, Any]) -> int:
        """Mint-and-append atomically (cross-thread safe); returns the
        revision stamped on the event."""
        with self._rev_lock:
            rev = self._next_revision()
            obj["revision"] = rev
            self.broadcaster.publish_nowait(rev, {"type": event_type, "object": obj})
        return rev

    # -- CRUDL ---------------------------------------------------------------

    def create_instance(
        self, config: InstanceConfig, instance_id: Optional[str] = None
    ) -> Dict[str, Any]:
        iid = instance_id or str(uuidlib.uuid4())
        if iid in self.instances:
            raise ValueError(f"instance {iid} already exists")
        if self._kickoff is None:
            # Real engine path: validate the options string pre-fork so a bad
            # config is a 422 at create time, not a crash discovered later.
            from ..engine.server import parse_engine_options
            from .instance import InvalidInstanceConfig

            try:
                parse_engine_options(config.options)
            except Exception as e:
                raise InvalidInstanceConfig(f"invalid engine options: {e}")
        kwargs = {} if self._kickoff is None else {"kickoff": self._kickoff}
        instance = EngineInstance(
            iid, config, self.translator, log_dir=self.log_dir, **kwargs
        )
        overlaps = self.ledger.acquire(iid, config.chip_ids)
        if overlaps:
            logger.warning(
                "instance %s chips overlap live instances %s "
                "(controller must ensure the overlapping ones are asleep)",
                iid,
                overlaps,
            )
        result = instance.start()
        self.instances[iid] = instance
        published = dict(result)
        instance.last_revision = self._publish("CREATED", published)
        result["revision"] = instance.last_revision
        logger.info("created instance %s (rev %s)", iid, instance.last_revision)
        return result

    def _on_instance_stopped(self, instance_id: str, exitcode) -> None:
        """Sentinel callback: the child died on its own."""
        instance = self.instances.get(instance_id)
        if instance is None:
            return
        self.ledger.release(instance_id)
        obj = instance.get_status()
        obj["exit_code"] = exitcode
        instance.last_revision = self._publish("STOPPED", obj)
        logger.warning(
            "instance %s stopped itself (exit code %s)", instance_id, exitcode
        )

    def stop_instance(self, instance_id: str, timeout: float = 10) -> Dict[str, Any]:
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        instance = self.instances[instance_id]
        instance.cancel_sentinel_watcher()
        result = instance.stop(timeout=timeout)
        del self.instances[instance_id]
        self.ledger.release(instance_id)
        published = dict(result)
        result["revision"] = self._publish("DELETED", published)
        logger.info("stopped instance %s", instance_id)
        return result

    def stop_all_instances(self, timeout: float = 10) -> Dict[str, Any]:
        stopped = []
        for iid in list(self.instances):
            self.stop_instance(iid, timeout=timeout)
            stopped.append(iid)
        return {"status": "all_stopped", "stopped_instances": stopped}

    def get_instance_status(self, instance_id: str) -> Dict[str, Any]:
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        return self.instances[instance_id].get_status()

    def get_all_instances_status(self) -> Dict[str, Any]:
        statuses = []
        running = 0
        for instance in self.instances.values():
            st = instance.get_status()
            statuses.append(st)
            if st["status"] == STATUS_RUNNING:
                running += 1
        return {
            "total_instances": len(statuses),
            "running_instances": running,
            "instances": statuses,
        }

    def list_instances(self) -> List[str]:
        return list(self.instances.keys())

    def get_instance_log_bytes(
        self, instance_id: str, start: int = 0, end: Optional[int] = None
    ):
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        return self.instances[instance_id].get_log_bytes(start, end)
