"""Multi-instance manager: CRUDL over engine instances + revisioned events.

Mirrors the reference's `VllmMultiProcessManager` (launcher.py:344-515): a
monotonically increasing revision counter stamped on every lifecycle event
(CREATED / STOPPED / DELETED), duplicate-ID create is an error (REST maps it
to 409), stop is graceful-then-kill, and a crashed child produces a STOPPED
event with its exit code via the sentinel watcher.

TPU delta: a `ChipLedger` records which chip sets are held by live instance
processes, and the manager *enforces* it: on TPU a chip has exactly one
process-holder at a time (a second PJRT client blocks in init), so creating
an instance whose chips overlap an AWAKE holder can only wedge — the
launcher refuses with 409. Overlap with holders that are all ASLEEP (devices
released; see engine/sleep.py) is the product's time-sharing path and is
allowed. The dual-pods controller remains the party that orchestrates who
sleeps when; the ledger is the node-local safety net against a controller
bug silently double-booking a chip.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import threading
import time
import urllib.error
import urllib.request
import uuid as uuidlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from prometheus_client import Gauge, Histogram

from ..utils import faults, tracing
from ..utils.events import EventBroadcaster
from .chiptranslator import ChipTranslator
from .instance import EngineInstance, InstanceConfig

logger = logging.getLogger(__name__)

#: Launcher -> engine-child admin RPC latency (the hop between the
#: controller-visible fma_http_latency_seconds and the engine's own verb
#: histograms — without it a slow actuation cannot be attributed to this
#: leg). One observation per HTTP attempt; `outcome` separates the retry
#: vocabulary: ok / http_<code> / refused (retried) / timeout /
#: unreachable. Exposed by the launcher's GET /metrics (docs/metrics.md).
LAUNCHER_RPC_SECONDS = Histogram(
    "fma_launcher_rpc_seconds",
    "Latency of launcher -> engine-child admin RPCs",
    ["verb", "outcome"],
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 2, 5, 15, 60, 300),
)

# Fleet rollup (docs/launcher.md "The fleet block"): node-level SLO /
# goodput / demand aggregates over every live engine child's GET
# /v1/stats, refreshed by fleet_rollup() on instance-list and /metrics
# reads — the one-scrape fleet view the multi-model scheduler (ROADMAP
# item 1) and the fleet bench consume.
LAUNCHER_FLEET_INSTANCES = Gauge(
    "fma_launcher_fleet_instances",
    "Engine instances by stats-poll outcome",
    ["state"],  # reporting | unreachable
)
LAUNCHER_FLEET_QUEUE_DEPTH = Gauge(
    "fma_launcher_fleet_queue_depth",
    "Waiting + in-flight requests summed over reporting instances",
)
LAUNCHER_FLEET_ARRIVAL_RATE = Gauge(
    "fma_launcher_fleet_arrival_rate",
    "Summed per-instance request arrival-rate EWMAs (requests/s)",
)
LAUNCHER_FLEET_SLO_ATTAINMENT = Gauge(
    "fma_launcher_fleet_slo_attainment",
    "Fraction of SLO-judged requests that met every configured target "
    "across the fleet (1.0 when nothing has been judged yet)",
)
LAUNCHER_FLEET_GOODPUT_TOKENS = Gauge(
    "fma_launcher_fleet_goodput_tokens",
    "Cumulative generated tokens from SLO-met requests, fleet-wide",
)
LAUNCHER_FLEET_ACTUATIONS_PER_HOUR = Gauge(
    "fma_launcher_fleet_actuations_per_hour",
    "Summed per-instance actuation rates (swap+sleep+wake per uptime "
    "hour)",
)
LAUNCHER_FLEET_RESIDENT_VARIANTS = Gauge(
    "fma_launcher_fleet_resident_variants",
    "Device-resident model variants summed over reporting instances "
    "(base included per instance)",
)
LAUNCHER_FLEET_CORESIDENT_SAVED_BYTES = Gauge(
    "fma_launcher_fleet_coresident_saved_bytes",
    "HBM bytes saved fleet-wide by co-resident variants sharing their "
    "base's device tensors (vs one full copy per variant)",
)

STATUS_STOPPED = "stopped"
STATUS_RUNNING = "running"

# probe_instance_state vocabulary: "still booting" (connected but no answer
# yet) and "crashed" (nothing listening) are DIFFERENT failure domains — a
# supervisor must never restart an instance that is merely slow to bind.
PROBE_AWAKE = "awake"
PROBE_RELEASED = "released"  # asleep AND devices released: chip is free
PROBE_REFUSED = "refused"  # nothing listening: crashed or not yet bound
PROBE_TIMEOUT = "timeout"  # listening but slow: booting / busy, NOT dead
PROBE_ERROR = "error"  # unparseable options, DNS, test fakes, ...


class ChipConflict(Exception):
    """Requested chips overlap an instance that is (or may be) awake."""

    def __init__(self, instance_id: str, blockers: List[str]) -> None:
        super().__init__(
            f"instance {instance_id}: chips held by awake (or not-yet-probeable) "
            f"instance(s) {blockers}; a TPU chip has one holder — sleep them first"
        )
        self.instance_id = instance_id
        self.blockers = blockers


class SwapFailed(Exception):
    """The engine child rejected (or never answered) a model hot-swap."""

    def __init__(self, instance_id: str, status: int, detail: str) -> None:
        super().__init__(
            f"swap of instance {instance_id} failed ({status}): {detail}"
        )
        self.instance_id = instance_id
        self.status = status
        self.detail = detail


class PrefetchFailed(Exception):
    """The engine child rejected (or never answered) a prefetch verb."""

    def __init__(self, instance_id: str, status: int, detail: str) -> None:
        super().__init__(
            f"prefetch on instance {instance_id} failed ({status}): {detail}"
        )
        self.instance_id = instance_id
        self.status = status
        self.detail = detail


class ResidentsFailed(Exception):
    """The engine child rejected (or never answered) a resident-set verb.
    Status 409 carries the engine's explicit admission rejection (cap /
    HBM budget / detach-while-live) — the caller's cue to fall back to
    the swap path."""

    def __init__(self, instance_id: str, status: int, detail: str) -> None:
        super().__init__(
            f"residents verb on instance {instance_id} failed "
            f"({status}): {detail}"
        )
        self.instance_id = instance_id
        self.status = status
        self.detail = detail


class StatsFailed(Exception):
    """The engine child never answered a stats poll (fleet rollup marks
    the instance unreachable instead of failing the whole read)."""

    def __init__(self, instance_id: str, status: int, detail: str) -> None:
        super().__init__(
            f"stats on instance {instance_id} failed ({status}): {detail}"
        )
        self.instance_id = instance_id
        self.status = status
        self.detail = detail


class MigrateFailed(Exception):
    """A live-request migration step failed (or was refused). Status 409
    carries the engine's explicit precondition refusal — identity
    mismatch, co-resident variants attached, spent fence, no capacity —
    after which nothing was displaced. Any other status means recovery
    already ran on the engines (source resumed locally or aborted the
    fenced bundle); the streams survived, the handoff didn't."""

    def __init__(self, instance_id: str, status: int, detail: str) -> None:
        super().__init__(
            f"migrate on instance {instance_id} failed ({status}): {detail}"
        )
        self.instance_id = instance_id
        self.status = status
        self.detail = detail


class DrainFailed(Exception):
    """A node-drain pass could not move the instance's remaining live
    work to a sibling (no eligible sibling, or a migrate pass failed)."""

    def __init__(self, instance_id: str, status: int, detail: str) -> None:
        super().__init__(
            f"drain of instance {instance_id} failed ({status}): {detail}"
        )
        self.instance_id = instance_id
        self.status = status
        self.detail = detail


def probe_instance_state(
    instance: "EngineInstance", timeout: float = 2.0
) -> str:
    """Classified probe of an instance's engine admin API (one of the
    PROBE_* constants). Unlike a bare reachable/unreachable check this
    separates connection-refused (nothing bound to the port: crashed, or
    the child hasn't reached its listen() yet) from timeout (something IS
    listening but slow to answer: booting, compiling, or busy) — the
    supervisor and chip-exclusivity logic weigh those differently."""
    try:
        from ..engine.server import parse_engine_options

        port = parse_engine_options(instance.config.options).port
    except Exception:
        return PROBE_ERROR
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/is_sleeping", timeout=timeout
        ) as resp:
            body = json.loads(resp.read() or b"{}")
    except urllib.error.URLError as e:
        reason = getattr(e, "reason", None)
        if isinstance(reason, ConnectionRefusedError):
            return PROBE_REFUSED
        if isinstance(reason, (TimeoutError, socket.timeout)):
            return PROBE_TIMEOUT
        return PROBE_ERROR
    except ConnectionRefusedError:
        return PROBE_REFUSED
    except (TimeoutError, socket.timeout):
        return PROBE_TIMEOUT
    except Exception:
        return PROBE_ERROR
    if body.get("is_sleeping", False) and body.get(
        "devices_released", False
    ):
        return PROBE_RELEASED
    return PROBE_AWAKE


def probe_instance_awake(instance: "EngineInstance") -> Optional[bool]:
    """Ask the instance's engine admin API whether it still holds its chips.

    Returns True ("awake": serving, or sleeping with the TPU client still
    open — either way the chip is held), False (asleep AND devices released
    — the chip is genuinely free), or None (engine not reachable — still
    booting, crashed, or a test fake). For the supervisor-facing
    distinction between those None cases, use probe_instance_state."""
    state = probe_instance_state(instance)
    if state == PROBE_AWAKE:
        return True
    if state == PROBE_RELEASED:
        return False
    return None


class ChipLedger:
    """Node-local truth of which live instance holds which chips (and which
    model each holder currently serves — hot-swap rebinds the model without
    touching the chip set, so the holder entry survives swaps unchanged)."""

    def __init__(self) -> None:
        self._held: Dict[str, List[str]] = {}  # instance_id -> chip_ids
        self._models: Dict[str, str] = {}  # instance_id -> served model
        #: instance_id -> model hinted/staged via the prefetch verb: the
        #: controller's "predicted next model" for this holder. Cleared
        #: when the hint is consumed (swap to that model), aborted, or
        #: the holder releases its chips.
        self._prefetched: Dict[str, str] = {}
        #: instance_id -> compact tiered-pool summary (pooled models,
        #: deduped host residency, dedup savings, disk-tier bytes, staged
        #: manifests) from the holder's last swap/prefetch answer — what a
        #: multi-model scheduler reads to pick a warm victim/target
        #: without an extra engine round trip.
        self._pools: Dict[str, Dict[str, Any]] = {}
        #: instance_id -> transfer mode of the holder's last swap ("off" |
        #: "int8" | "fp8"): whether this holder actuates compressed
        #: (docs/perf.md "Compressed actuation") — the byte-cost signal a
        #: scheduler weighs against the models' numerics requirements.
        self._quant: Dict[str, str] = {}
        #: instance_id -> resident-set summary from the holder's last
        #: /v1/residents answer (docs/launcher.md "The resident-set
        #: ledger"): which sibling variants are device-resident alongside
        #: the base, the variant HBM budget/usage, and the shared-base
        #: dedup savings — the zero-actuation routing options a
        #: multi-model scheduler weighs BEFORE pricing any swap.
        self._residents: Dict[str, Dict[str, Any]] = {}

    def overlapping(
        self, chip_ids: Optional[List[str]], exclude: Optional[str] = None
    ) -> List[str]:
        """Instance IDs whose recorded chip sets overlap `chip_ids`."""
        chips = set(chip_ids or [])
        return [
            iid
            for iid, held in self._held.items()
            if iid != exclude and chips & set(held)
        ]

    def acquire(self, instance_id: str, chip_ids: Optional[List[str]]) -> List[str]:
        """Record ownership; returns the list of instance IDs whose chip sets
        overlap (empty = clean placement)."""
        overlaps = self.overlapping(chip_ids, exclude=instance_id)
        self._held[instance_id] = sorted(set(chip_ids or []))
        return overlaps

    def release(self, instance_id: str) -> None:
        self._held.pop(instance_id, None)
        self._models.pop(instance_id, None)
        self._prefetched.pop(instance_id, None)
        self._pools.pop(instance_id, None)
        self._quant.pop(instance_id, None)
        self._residents.pop(instance_id, None)

    def set_model(self, instance_id: str, model: str) -> None:
        """Record which model a holder serves (updated on hot-swap). A
        swap to the prefetched model consumes the prefetch hint."""
        if instance_id in self._held:
            self._models[instance_id] = model
            if self._prefetched.get(instance_id) == model:
                self._prefetched.pop(instance_id, None)

    def set_prefetched(self, instance_id: str, model: Optional[str]) -> None:
        """Record (or with None, clear) the model a holder has staged via
        the prefetch verb."""
        if model is None:
            self._prefetched.pop(instance_id, None)
        elif instance_id in self._held:
            self._prefetched[instance_id] = model

    def set_pool(
        self, instance_id: str, pool: Optional[Dict[str, Any]]
    ) -> None:
        """Record the holder's tiered-pool shape from an engine swap /
        prefetch answer (None or a pool-less answer clears nothing — the
        last known summary stays until the holder releases its chips)."""
        if pool is None or instance_id not in self._held:
            return
        chunks = pool.get("chunks") or {}
        self._pools[instance_id] = {
            "models": list(pool.get("models") or []),
            "bytes_used": pool.get("bytes_used", 0),
            "budget_bytes": pool.get("budget_bytes", 0),
            "dedup_saved_bytes": chunks.get("dedup_saved_bytes", 0),
            "disk_bytes": chunks.get("disk_bytes", 0),
            "staged_manifests": list(pool.get("staged_manifests") or []),
        }

    def set_quant(self, instance_id: str, quant: Optional[str]) -> None:
        """Record the transfer mode of a holder's last swap answer (None
        / unknown answers leave the last known value)."""
        if quant and instance_id in self._held:
            self._quant[instance_id] = quant

    def set_residents(
        self, instance_id: str, view: Optional[Dict[str, Any]]
    ) -> None:
        """Record a holder's resident set from an engine /v1/residents
        answer (the residents_view block every attach/detach returns).
        Compacted to what a scheduler reads: membership, budget/usage,
        and the shared-base savings the co-residency is buying."""
        if view is None or instance_id not in self._held:
            return
        ledger = view.get("ledger") or {}
        self._residents[instance_id] = {
            "base": view.get("base"),
            "resident_variants": int(view.get("resident_variants", 1)),
            "resident_variants_cap": int(
                view.get("resident_variants_cap", 1)
            ),
            "residents": sorted(view.get("residents") or {}),
            "variant_hbm_bytes": int(view.get("variant_hbm_bytes", 0)),
            "variant_hbm_budget_bytes": int(
                view.get("variant_hbm_budget_bytes", 0)
            ),
            "bytes_saved": int(ledger.get("bytes_saved", 0)),
        }

    def residents(self) -> Dict[str, Dict[str, Any]]:
        return dict(self._residents)

    def quants(self) -> Dict[str, str]:
        return dict(self._quant)

    def holders(self) -> Dict[str, List[str]]:
        return dict(self._held)

    def models(self) -> Dict[str, str]:
        return dict(self._models)

    def prefetched(self) -> Dict[str, str]:
        return dict(self._prefetched)

    def pools(self) -> Dict[str, Dict[str, Any]]:
        return dict(self._pools)


@dataclass
class RestartPolicy:
    """Supervised-restart knobs for crashed engine children.

    ``budget`` restarts per crash loop (0 disables supervision — the
    launcher then only reports the death, the pre-existing behavior, and
    the dual-pods controller heals by re-pairing). Delays grow
    ``backoff_s * 2**attempt`` up to ``backoff_max_s``, with up to
    ``jitter_frac`` random extra so a node full of children crashed by one
    cause doesn't restart in lockstep. A child that stays up longer than
    ``reset_window_s`` earns its crash counter back — the budget bounds
    crash *loops*, not total restarts over a long instance lifetime."""

    budget: int = 0
    backoff_s: float = 0.5
    backoff_max_s: float = 30.0
    jitter_frac: float = 0.2
    reset_window_s: float = 300.0


@dataclass
class _RestartState:
    attempts: int = 0
    last_crash: float = 0.0
    timer: Optional[threading.Timer] = None
    #: set by _cancel_restart under the restart lock; a timer body that
    #: already started (Timer.cancel is a no-op then) re-checks this
    #: before forking, so an explicit stop can never race an orphan child
    cancelled: bool = False


class EngineProcessManager:
    def __init__(
        self,
        translator: ChipTranslator,
        log_dir: str = "",
        kickoff=None,
        enforce_chip_exclusivity: bool = True,
        awake_probe: Optional[
            Callable[["EngineInstance"], Optional[bool]]
        ] = None,
        restart_policy: Optional[RestartPolicy] = None,
    ) -> None:
        self.instances: Dict[str, EngineInstance] = {}
        self.translator = translator
        if log_dir:
            import os

            os.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        self.ledger = ChipLedger()
        self.broadcaster = EventBroadcaster()
        self._revision = 0
        # create/sentinel publish on the loop thread; stop_instance publishes
        # from the REST handler's executor thread — revision minting and the
        # buffer append must be one atomic step or a watcher can skip events
        self._rev_lock = threading.Lock()
        self._kickoff = kickoff
        # With a fake kickoff there is no engine admin API to probe, so the
        # sleep state of an overlapping holder is unknowable — enforcement
        # stays opt-in for such managers (tests pass a probe or disable).
        self.enforce_chip_exclusivity = enforce_chip_exclusivity
        self._awake_probe = awake_probe or probe_instance_awake
        # Crash supervision (docs/operations.md "Self-healing"): a child
        # death becomes a backoff-scheduled in-place restart instead of a
        # wait for the controller's minutes-long re-pair path.
        self.restart_policy = restart_policy
        self._restart_states: Dict[str, _RestartState] = {}
        # RLock: _restart_instance holds it across its whole body (so a
        # concurrent stop_instance serializes against the fork) and its
        # spawn-failure path re-enters via _restart_allowed/_schedule
        self._restart_lock = threading.RLock()
        self._loop = None  # captured from the sentinel callback's loop
        # fleet_rollup cache: instance-list reads and /metrics scrapes
        # both refresh the rollup; a short TTL keeps back-to-back reads
        # from double-polling every child
        self._fleet_lock = threading.Lock()
        self._fleet_cache: Optional[tuple] = None  # (monotonic_t, block)

    # -- revisions -----------------------------------------------------------

    @property
    def revision(self) -> int:
        return self._revision

    def _next_revision(self) -> int:
        self._revision += 1
        return self._revision

    def _publish(self, event_type: str, obj: Dict[str, Any]) -> int:
        """Mint-and-append atomically (cross-thread safe); returns the
        revision stamped on the event."""
        with self._rev_lock:
            rev = self._next_revision()
            obj["revision"] = rev
            self.broadcaster.publish_nowait(rev, {"type": event_type, "object": obj})
        return rev

    # -- CRUDL ---------------------------------------------------------------

    def create_instance(
        self, config: InstanceConfig, instance_id: Optional[str] = None
    ) -> Dict[str, Any]:
        """Traced entry: the span is active across the fork, so the child
        inherits it via FMA_TRACEPARENT (instance.start stamps the env)
        and its engine.start span joins this trace."""
        with tracing.span(
            "launcher.create_instance", instance=instance_id or ""
        ):
            return self._create_instance_impl(config, instance_id)

    def _create_instance_impl(
        self, config: InstanceConfig, instance_id: Optional[str] = None
    ) -> Dict[str, Any]:
        iid = instance_id or str(uuidlib.uuid4())
        if iid in self.instances:
            raise ValueError(f"instance {iid} already exists")
        if self._kickoff is None:
            # Real engine path: validate the options string pre-fork so a bad
            # config is a 422 at create time, not a crash discovered later.
            from ..engine.server import parse_engine_options
            from .instance import InvalidInstanceConfig

            try:
                parse_engine_options(config.options)
            except Exception as e:
                raise InvalidInstanceConfig(f"invalid engine options: {e}")
        overlaps = self.ledger.overlapping(config.chip_ids, exclude=iid)
        if overlaps and self.enforce_chip_exclusivity:
            # Allowed only if EVERY overlapping holder is verifiably asleep
            # with devices released. Unreachable == possibly booting ==
            # treated awake: refusing a race beats wedging the chip.
            blockers = []
            for other in overlaps:
                inst = self.instances.get(other)
                if inst is None:
                    # stale ledger entry (a failed create); drop, not block
                    self.ledger.release(other)
                    continue
                if self._awake_probe(inst) is not False:
                    blockers.append(other)
            if blockers:
                raise ChipConflict(iid, blockers)
        elif overlaps:
            logger.warning(
                "instance %s chips overlap live instances %s "
                "(enforcement off: controller must ensure they are asleep)",
                iid,
                overlaps,
            )
        kwargs = {} if self._kickoff is None else {"kickoff": self._kickoff}
        instance = EngineInstance(
            iid, config, self.translator, log_dir=self.log_dir, **kwargs
        )
        result = instance.start()
        # record ownership only once the process actually exists — a failed
        # start must not leak a chips hold
        self.ledger.acquire(iid, config.chip_ids)
        try:
            from ..engine.server import parse_engine_options

            self.ledger.set_model(
                iid, parse_engine_options(config.options).model
            )
        except Exception:  # noqa: BLE001 — fake-kickoff tests use free-form options
            pass
        self.instances[iid] = instance
        published = dict(result)
        instance.last_revision = self._publish("CREATED", published)
        result["revision"] = instance.last_revision
        logger.info("created instance %s (rev %s)", iid, instance.last_revision)
        return result

    def _on_instance_stopped(self, instance_id: str, exitcode) -> None:
        """Sentinel callback: the child died on its own. Publishes STOPPED
        (wire behavior unchanged), then — when a restart policy is armed
        and the crash-loop budget allows — keeps the ChipLedger hold (the
        chips stay earmarked for the comeback; a concurrent create must
        not steal them) and schedules a supervised restart."""
        instance = self.instances.get(instance_id)
        if instance is None:
            return
        will_restart = self._restart_allowed(instance_id)
        if not will_restart:
            self.ledger.release(instance_id)
        obj = instance.get_status()
        obj["exit_code"] = exitcode
        instance.last_revision = self._publish("STOPPED", obj)
        logger.warning(
            "instance %s stopped itself (exit code %s)", instance_id, exitcode
        )
        if will_restart:
            try:
                import asyncio

                self._loop = asyncio.get_running_loop()
            except RuntimeError:
                pass
            self._schedule_restart(instance_id, exitcode)

    # -- crash supervision ---------------------------------------------------

    def _restart_allowed(self, instance_id: str) -> bool:
        pol = self.restart_policy
        if pol is None or pol.budget <= 0:
            return False
        if instance_id not in self.instances:
            return False
        with self._restart_lock:
            st = self._restart_states.setdefault(instance_id, _RestartState())
            now = time.monotonic()
            if (
                st.attempts
                and now - st.last_crash > pol.reset_window_s
            ):
                # survived a full window since the last crash: not a loop
                st.attempts = 0
            if st.attempts >= pol.budget:
                logger.error(
                    "instance %s crash-looped through its restart budget "
                    "(%d); leaving it stopped", instance_id, pol.budget,
                )
                return False
            return True

    def _schedule_restart(self, instance_id: str, exitcode) -> None:
        """Publish RESTARTING and arm the backoff timer for one attempt."""
        pol = self.restart_policy
        instance = self.instances.get(instance_id)
        if pol is None or instance is None:
            return
        with self._restart_lock:
            st = self._restart_states.setdefault(instance_id, _RestartState())
            attempt = st.attempts
            st.attempts += 1
            st.last_crash = time.monotonic()
            delay = min(pol.backoff_max_s, pol.backoff_s * (2 ** attempt))
            delay *= 1.0 + random.uniform(0.0, max(0.0, pol.jitter_frac))
            delay = min(delay, pol.backoff_max_s)  # cap is a hard ceiling
            timer = threading.Timer(
                delay,
                self._restart_instance,
                args=(instance_id, attempt + 1, st),
            )
            timer.daemon = True
            st.timer = timer
        obj = instance.get_status()
        obj.update(
            exit_code=exitcode,
            restart_attempt=attempt + 1,
            restart_budget=pol.budget,
            backoff_s=round(delay, 3),
        )
        instance.last_revision = self._publish("RESTARTING", obj)
        logger.warning(
            "instance %s: supervised restart %d/%d in %.2fs",
            instance_id, attempt + 1, pol.budget, delay,
        )
        timer.start()

    def _restart_instance(
        self, instance_id: str, attempt: int, st: _RestartState
    ) -> None:
        """Backoff-timer body: re-fork the child from the instance's
        CURRENT (engine-truth rewritten) options — a restarted instance
        comes back serving its last-swapped model — then reconcile the
        ChipLedger and re-arm crash detection.

        Runs under the restart lock end to end: Timer.cancel is a no-op
        once this body has started, so an explicit stop_instance racing it
        serializes on the lock instead — either the restart completes
        first (and the stop then stops the fresh child and releases the
        ledger), or the cancel lands first (``st.cancelled``) and no child
        is forked."""
        with self._restart_lock:
            if st.cancelled:
                return  # explicit stop won the race
            instance = self.instances.get(instance_id)
            if instance is None:
                return  # stopped/deleted while the backoff ran
            if instance.process is not None and instance.process.is_alive():
                return  # never restart a live child (manual intervention)
            try:
                with tracing.span(
                    "launcher.restart",
                    instance=instance_id,
                    attempt=attempt,
                ):
                    faults.fire("instance.spawn")
                    # append to the existing log: the crash forensics above
                    # the restart marker are exactly what the operator needs
                    instance.start(fresh_log=False, restart=True)
            except Exception as e:  # noqa: BLE001 — spawn failed: retry
                logger.warning(
                    "instance %s restart attempt %d failed to spawn: %s",
                    instance_id, attempt, e,
                )
                if self._restart_allowed(instance_id):
                    self._schedule_restart(instance_id, None)
                else:
                    self.ledger.release(instance_id)
                return
            # reconcile the ledger: the hold was kept across the crash
            # window; acquire is idempotent, and the model comes from the
            # rewritten options (what the child will actually serve)
            self.ledger.acquire(instance_id, instance.config.chip_ids)
            try:
                from ..engine.server import parse_engine_options

                self.ledger.set_model(
                    instance_id,
                    parse_engine_options(instance.config.options).model,
                )
            except Exception:  # noqa: BLE001 — free-form options
                pass
            obj = instance.get_status()
            obj["restart_attempt"] = attempt
            instance.last_revision = self._publish("RESTARTED", obj)
            logger.info(
                "instance %s restarted (attempt %d, pid %s)",
                instance_id, attempt,
                instance.process.pid if instance.process else None,
            )
        loop = self._loop
        if loop is not None and loop.is_running():
            # crash detection must be re-armed on the event loop thread
            loop.call_soon_threadsafe(self._rearm_sentinel, instance_id)

    def _rearm_sentinel(self, instance_id: str) -> None:
        instance = self.instances.get(instance_id)
        if instance is None:
            return
        try:
            instance.start_sentinel_watcher(self._on_instance_stopped)
        except RuntimeError:
            logger.warning(
                "no running loop; sentinel not re-armed for %s", instance_id
            )

    def _cancel_restart(self, instance_id: str) -> None:
        with self._restart_lock:
            st = self._restart_states.pop(instance_id, None)
            if st is not None:
                st.cancelled = True
                if st.timer is not None:
                    st.timer.cancel()

    def stop_instance(self, instance_id: str, timeout: float = 10) -> Dict[str, Any]:
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        instance = self.instances[instance_id]
        instance.cancel_sentinel_watcher()
        self._cancel_restart(instance_id)  # an explicit stop is not a crash
        result = instance.stop(timeout=timeout)
        del self.instances[instance_id]
        self.ledger.release(instance_id)
        published = dict(result)
        result["revision"] = self._publish("DELETED", published)
        logger.info("stopped instance %s", instance_id)
        return result

    def swap_instance(
        self,
        instance_id: str,
        model: str,
        checkpoint_dir: str = "",
        timeout: float = 300,
    ) -> Dict[str, Any]:
        """Traced entry for the launcher swap verb (the engine-side tree
        hangs off the launcher.rpc child span via traceparent)."""
        with tracing.span(
            "launcher.swap", instance=instance_id, model=model
        ):
            return self._swap_instance_impl(
                instance_id, model, checkpoint_dir, timeout
            )

    def _swap_instance_impl(
        self,
        instance_id: str,
        model: str,
        checkpoint_dir: str = "",
        timeout: float = 300,
    ) -> Dict[str, Any]:
        """Hot-swap the model a live instance serves: forward to the engine
        child's POST /v1/swap (no stop/start cycle — the chip set, the
        process, and its ChipLedger hold are all unchanged), then bring the
        stored config and ledger in line with the model actually served."""
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        instance = self.instances[instance_id]
        from ..engine.server import parse_engine_options

        try:
            previous = parse_engine_options(instance.config.options).model
        except Exception:
            previous = ""
        # The request id makes the verb safely recoverable: if the POST
        # times out with the swap possibly still executing, we do NOT
        # re-send (that could swap twice) — we poll GET /v1/swap and accept
        # the committed record carrying OUR id as the answer.
        request_id = uuidlib.uuid4().hex
        try:
            body = self._engine_request(
                instance_id, "POST", "/v1/swap",
                {
                    "model": model,
                    "checkpoint_dir": checkpoint_dir,
                    "request_id": request_id,
                },
                timeout, SwapFailed,
            )
        except SwapFailed as e:
            if e.status != 504:
                raise
            body = self._recover_swap_result(instance_id, request_id, e)
        from .instance import replace_model_option

        # rewrite from the ENGINE's answer, not the request: a pool hit
        # restores the pooled runtime's own checkpoint identity, and the
        # stored options must describe what the child actually serves
        # (a restart rebuilds from them)
        instance.config.options = replace_model_option(
            instance.config.options,
            model,
            checkpoint_dir=body.get("checkpoint_dir") or checkpoint_dir,
        )
        self.ledger.set_model(instance_id, model)
        self.ledger.set_pool(instance_id, body.get("pool"))
        self.ledger.set_quant(instance_id, body.get("quant"))
        obj = instance.get_status()
        obj["swap"] = body
        instance.last_revision = self._publish("SWAPPED", obj)
        logger.info(
            "swapped instance %s: %s -> %s (pool_hit=%s, rev %s)",
            instance_id, previous, model, body.get("pool_hit"),
            instance.last_revision,
        )
        return {
            "instance_id": instance_id,
            "model": model,
            "previous_model": previous,
            "swap": body,
            "revision": instance.last_revision,
        }

    def _recover_swap_result(
        self,
        instance_id: str,
        request_id: str,
        timeout_exc: "SwapFailed",
        window_s: float = 10.0,
        poll_s: float = 0.5,
    ) -> Dict[str, Any]:
        """Timed-out swap recovery: poll the engine's committed-swap record
        for our request id. Found => the swap happened exactly once and
        this is its result; not found within the window => surface the
        original timeout as a 504 (the caller knows the verb may still be
        executing and can widen its timeout)."""
        deadline = time.monotonic() + window_s
        while time.monotonic() < deadline:
            try:
                body = self._engine_request(
                    instance_id, "GET", "/v1/swap", None,
                    min(5.0, window_s), SwapFailed, retries=1,
                )
            except SwapFailed:
                body = {}
            if body.get("request_id") == request_id:
                logger.info(
                    "swap on instance %s recovered via request id after a "
                    "timeout", instance_id,
                )
                return body
            time.sleep(poll_s)
        raise SwapFailed(
            instance_id, 504,
            f"swap timed out and no committed record with request id "
            f"{request_id} appeared within {window_s}s "
            f"({timeout_exc.detail})",
        )

    @staticmethod
    def _is_connection_refused(e: BaseException) -> bool:
        if isinstance(e, (ConnectionRefusedError, faults.FaultError)):
            # an injected launcher.rpc fault models exactly this class of
            # failure: the request never reached the engine
            return True
        if isinstance(e, urllib.error.URLError):
            return isinstance(
                getattr(e, "reason", None), ConnectionRefusedError
            )
        return False

    @staticmethod
    def _is_timeout(e: BaseException) -> bool:
        if isinstance(e, (TimeoutError, socket.timeout)):
            return True
        if isinstance(e, urllib.error.URLError):
            return isinstance(
                getattr(e, "reason", None), (TimeoutError, socket.timeout)
            )
        return False

    def _engine_request(
        self,
        instance_id: str,
        method: str,
        api_path: str,
        body: Optional[Dict[str, Any]],
        timeout: float,
        exc_cls,
        retries: int = 2,
        retry_backoff_s: float = 0.2,
    ) -> Dict[str, Any]:
        """Forward an admin verb to a live instance's engine child; maps
        stored-options/HTTP failures onto `exc_cls(instance_id, status,
        detail)` the REST layer turns into 4xx/502/503.

        Connection-refused is retried up to ``retries`` times with
        exponential backoff + jitter: refused means the request never
        reached the engine (crash window mid-restart, child not yet bound),
        so a retry is safe for EVERY verb. A TIMEOUT is never retried here
        — the request may be executing (a timed-out swap re-sent blindly
        could swap twice); it raises with status **504** (vs 502 for
        unreachable) so callers with an idempotent recovery path
        (swap_instance's request-id replay) can take it."""
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        instance = self.instances[instance_id]
        from ..engine.server import parse_engine_options

        try:
            opts = parse_engine_options(instance.config.options)
        except Exception as e:
            # free-form options are tolerated at create time (fake-kickoff
            # managers); admin verbs on such an instance are a client error
            raise exc_cls(
                instance_id, 400,
                f"stored options are not engine options: {e}",
            )
        verb = f"{method} {api_path}"
        # The RPC span: the engine-side handler adopts the traceparent we
        # send, so the child's swap/sleep tree hangs off this span in one
        # coherent trace across the process boundary (docs/tracing.md).
        rpc_sp = tracing.begin("launcher.rpc", instance=instance_id, verb=verb)
        req = urllib.request.Request(
            f"http://127.0.0.1:{opts.port}{api_path}",
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method=method,
        )
        tp = rpc_sp.traceparent()
        if tp:
            req.add_header("Traceparent", tp)
        attempt = 0
        while True:
            t0 = time.monotonic()
            try:
                faults.fire("launcher.rpc")
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    out = json.loads(resp.read() or b"{}")
                LAUNCHER_RPC_SECONDS.labels(
                    verb=verb, outcome="ok"
                ).observe(time.monotonic() - t0)
                rpc_sp.set(outcome="ok", attempts=attempt + 1)
                rpc_sp.end()
                return out
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")[:500]
                LAUNCHER_RPC_SECONDS.labels(
                    verb=verb, outcome=f"http_{e.code}"
                ).observe(time.monotonic() - t0)
                rpc_sp.set(outcome=f"http_{e.code}")
                rpc_sp.end()
                raise exc_cls(instance_id, e.code, detail)
            except Exception as e:  # noqa: BLE001 — refused, timeout, ...
                if self._is_connection_refused(e) and attempt < retries:
                    LAUNCHER_RPC_SECONDS.labels(
                        verb=verb, outcome="refused"
                    ).observe(time.monotonic() - t0)
                    attempt += 1
                    delay = retry_backoff_s * (2 ** (attempt - 1))
                    delay *= 1.0 + random.random()  # jitter: no lockstep
                    logger.warning(
                        "engine %s refused %s %s (attempt %d/%d); "
                        "retrying in %.2fs",
                        instance_id, method, api_path, attempt, retries,
                        delay,
                    )
                    time.sleep(min(delay, 2.0))
                    continue
                if self._is_timeout(e):
                    LAUNCHER_RPC_SECONDS.labels(
                        verb=verb, outcome="timeout"
                    ).observe(time.monotonic() - t0)
                    rpc_sp.set(outcome="timeout")
                    rpc_sp.end()
                    raise exc_cls(instance_id, 504, f"engine timed out: {e}")
                LAUNCHER_RPC_SECONDS.labels(
                    verb=verb, outcome="unreachable"
                ).observe(time.monotonic() - t0)
                rpc_sp.set(outcome="unreachable")
                rpc_sp.end()
                raise exc_cls(instance_id, 502, f"engine unreachable: {e}")

    def prefetch_instance(
        self,
        instance_id: str,
        model: str,
        checkpoint_dir: str = "",
        timeout: float = 60,
    ) -> Dict[str, Any]:
        """Background-prefetch verb: have a live instance stage `model`'s
        weights host-resident (engine POST /v1/prefetch) while it keeps
        serving its current model, and record the hint in the ChipLedger —
        the dual-pods controller's way of warming the predicted next swap
        without touching the chip set or the serving process."""
        body = self._engine_request(
            instance_id, "POST", "/v1/prefetch",
            {"model": model, "checkpoint_dir": checkpoint_dir},
            timeout, PrefetchFailed,
        )
        # The hint is ADVISORY: it is recorded when the engine accepts the
        # staging and the background outcome is reconciled on status reads
        # (get_instance_prefetch drops it on failed/rejected/aborted) — a
        # controller that acts on the hint without having polled may still
        # get a cold build if the staging later failed.
        self.ledger.set_prefetched(instance_id, model)
        self.ledger.set_pool(instance_id, body.get("pool"))
        logger.info(
            "prefetch on instance %s: %s (state=%s)",
            instance_id, model, body.get("state"),
        )
        return {
            "instance_id": instance_id,
            "model": model,
            "prefetch": body,
        }

    def abort_instance_prefetch(
        self, instance_id: str, timeout: float = 90
    ) -> Dict[str, Any]:
        """Cancel an instance's in-flight prefetch (engine DELETE
        /v1/prefetch) and drop the ledger hint."""
        body = self._engine_request(
            instance_id, "DELETE", "/v1/prefetch", None, timeout,
            PrefetchFailed,
        )
        # keep the hint when there was nothing to abort because the
        # prefetch already COMPLETED: the staged weights are still pooled
        # and a swap to them is still warm — the hint is still true
        if body.get("aborted") or body.get("state") != "completed":
            self.ledger.set_prefetched(instance_id, None)
        return {
            "instance_id": instance_id,
            "prefetch": body,
        }

    def get_instance_prefetch(
        self, instance_id: str, timeout: float = 10
    ) -> Dict[str, Any]:
        """Prefetch status passthrough (engine GET /v1/prefetch). Also
        reconciles the advisory ledger hint: a staging that ended
        failed/rejected/aborted is no longer a warm next model."""
        body = self._engine_request(
            instance_id, "GET", "/v1/prefetch", None, timeout, PrefetchFailed
        )
        if body.get("state") in ("failed", "rejected", "aborted"):
            self.ledger.set_prefetched(instance_id, None)
        return {"instance_id": instance_id, "prefetch": body}

    def attach_instance_resident(
        self,
        instance_id: str,
        model: str,
        checkpoint_dir: str = "",
        timeout: float = 120,
    ) -> Dict[str, Any]:
        """Co-residency attach verb: have a live instance upload `model`'s
        delta leaves next to its base (engine POST /v1/residents) and
        route per-request from then on — the zero-swap alternative to
        swap_instance for sibling-variant traffic. The engine's explicit
        admission rejection (cap / HBM budget / cold source) surfaces as
        a 409 ResidentsFailed: the caller falls back to the swap path."""
        with tracing.span(
            "launcher.attach_resident", instance=instance_id, model=model
        ):
            body = self._engine_request(
                instance_id, "POST", "/v1/residents",
                {"model": model, "checkpoint_dir": checkpoint_dir},
                timeout, ResidentsFailed,
            )
        self.ledger.set_residents(instance_id, body)
        logger.info(
            "attached resident on instance %s: %s (handle=%s, "
            "wire_bytes=%s)",
            instance_id, body.get("model", model), body.get("handle"),
            body.get("wire_bytes"),
        )
        return {"instance_id": instance_id, "residents": body}

    def detach_instance_resident(
        self,
        instance_id: str,
        model: str,
        checkpoint_dir: str = "",
        timeout: float = 60,
    ) -> Dict[str, Any]:
        """Co-residency detach verb (engine DELETE /v1/residents): drop a
        variant's device delta — zero wire bytes; the content tiers keep
        every chunk, so re-attach stays delta-only."""
        body = self._engine_request(
            instance_id, "DELETE", "/v1/residents",
            {"model": model, "checkpoint_dir": checkpoint_dir},
            timeout, ResidentsFailed,
        )
        self.ledger.set_residents(instance_id, body)
        return {"instance_id": instance_id, "residents": body}

    def get_instance_residents(
        self, instance_id: str, timeout: float = 10
    ) -> Dict[str, Any]:
        """Resident-set passthrough (engine GET /v1/residents); refreshes
        the ledger's resident-set block as a side effect."""
        body = self._engine_request(
            instance_id, "GET", "/v1/residents", None, timeout,
            ResidentsFailed,
        )
        self.ledger.set_residents(instance_id, body)
        return {"instance_id": instance_id, "residents": body}

    # -- live request migration / node drain ---------------------------------

    def _parsed_opts(self, instance_id: str):
        """Parsed engine options of a live instance's stored config, or
        None when the options are free-form (fake-kickoff managers)."""
        from ..engine.server import parse_engine_options

        try:
            return parse_engine_options(
                self.instances[instance_id].config.options
            )
        except Exception:
            return None

    def _resolve_migration_dest(
        self, instance_id: str, model: str, dest_id: Optional[str]
    ) -> str:
        """Pick (or validate) the sibling instance a migration lands on.
        Eligibility here is only 'another live instance whose stored
        options serve the same model' — the engines themselves enforce
        the real identity gate (weight fingerprint / checkpoint path +
        page geometry) at import time."""
        if dest_id is not None:
            if dest_id == instance_id:
                raise MigrateFailed(
                    instance_id, 400,
                    "destination must be a different instance",
                )
            if dest_id not in self.instances:
                raise MigrateFailed(
                    instance_id, 404,
                    f"no such destination instance {dest_id}",
                )
            opts = self._parsed_opts(dest_id)
            if opts is None or opts.model != model:
                raise MigrateFailed(
                    instance_id, 409,
                    f"destination {dest_id} does not serve {model!r}; "
                    "migration needs a sibling with provable weight "
                    "identity",
                )
            return dest_id
        for other in self.instances:
            if other == instance_id:
                continue
            opts = self._parsed_opts(other)
            if opts is not None and opts.model == model:
                return other
        raise MigrateFailed(
            instance_id, 409,
            f"no sibling instance serves {model!r}; nothing to migrate to",
        )

    def _abort_migration_on_source(
        self, instance_id: str, token: str, timeout: float
    ) -> None:
        """Best-effort fenced abort after a failed import: the source
        resumes the parked bundle locally. A failure here is logged, not
        raised — the import failure stays the primary error, and the
        bundle remains fenced on the source for a later manual abort."""
        if not token:
            return
        try:
            self._engine_request(
                instance_id, "POST", "/v1/parked/abort",
                {"fence_token": token}, timeout, MigrateFailed,
            )
        except (MigrateFailed, KeyError) as e:
            logger.error(
                "migration abort on source %s failed (%s); the bundle "
                "stays fenced under token %s — POST /v1/parked/abort "
                "when the engine is reachable again",
                instance_id, e, token,
            )

    def migrate_instance(
        self,
        instance_id: str,
        dest_id: Optional[str] = None,
        timeout: float = 300,
    ) -> Dict[str, Any]:
        """Traced entry for the live-migration verb (docs/launcher.md)."""
        with tracing.span(
            "launcher.migrate", instance=instance_id, dest=dest_id or ""
        ):
            return self._migrate_instance_impl(instance_id, dest_id, timeout)

    def _migrate_instance_impl(
        self,
        instance_id: str,
        dest_id: Optional[str],
        timeout: float,
    ) -> Dict[str, Any]:
        """Transactional handoff of an instance's live work to a sibling
        serving the same model: export the fenced bundle (engine GET
        /v1/parked/{model}), import it on the destination (POST
        /v1/parked), release the source (POST /v1/parked/release) so it
        proxies every surviving stream to the destination's claims.

        Failure discipline mirrors the engine's drilled recoveries:

        * export failure — the bundle never left the source; the engine
          already resumed it locally, we just surface the error;
        * import refusal (409/400) or import timeout (504, never
          re-sent) — abort the fence so the source resumes locally;
        * import failure (5xx/502) — ONE blind retry: the fence makes it
          idempotent (a seated import replays its stored ack, a rolled-
          back one seats fresh); a second failure aborts back to the
          source.
        """
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        opts = self._parsed_opts(instance_id)
        if opts is None:
            raise MigrateFailed(
                instance_id, 400,
                "stored options are not engine options",
            )
        model = opts.model
        dest = self._resolve_migration_dest(instance_id, model, dest_id)
        doc = self._engine_request(
            instance_id, "GET", f"/v1/parked/{model}", None, timeout,
            MigrateFailed,
        )
        token = str((doc.get("fence") or {}).get("token") or "")
        try:
            ack = self._engine_request(
                dest, "POST", "/v1/parked", doc, timeout, MigrateFailed,
            )
        except MigrateFailed as e:
            if e.status in (400, 409, 504):
                # refusal (nothing displaced) or timeout (may still be
                # executing — never re-send): resume on the source
                self._abort_migration_on_source(instance_id, token, timeout)
                raise
            try:
                ack = self._engine_request(
                    dest, "POST", "/v1/parked", doc, timeout,
                    MigrateFailed,
                )
            except MigrateFailed:
                self._abort_migration_on_source(instance_id, token, timeout)
                raise
        dest_opts = self._parsed_opts(dest)
        dest_url = f"http://127.0.0.1:{dest_opts.port}" if dest_opts else ""
        rel = self._engine_request(
            instance_id, "POST", "/v1/parked/release",
            {
                "fence_token": token,
                "dest": dest_url,
                "claims": ack.get("claims") or {},
            },
            timeout, MigrateFailed,
        )
        result = {
            "instance_id": instance_id,
            "dest_id": dest,
            "model": model,
            "fence_token": token,
            "requests": int(ack.get("requests", 0)),
            "migrated": int(rel.get("migrated", 0)),
            "proxied": int(rel.get("proxied", 0)),
            "bytes": int(doc.get("nbytes", 0)),
            "import": {k: v for k, v in ack.items() if k != "claims"},
            "release": rel,
        }
        obj = self.instances[instance_id].get_status()
        obj["migration"] = {
            k: result[k]
            for k in (
                "dest_id", "model", "fence_token", "requests", "migrated",
                "proxied", "bytes",
            )
        }
        result["revision"] = self._publish("MIGRATED", obj)
        logger.info(
            "migrated instance %s -> %s: %d request(s), %d byte(s), "
            "%d stream(s) proxied (rev %s)",
            instance_id, dest, result["requests"], result["bytes"],
            result["proxied"], result["revision"],
        )
        return result

    def drain_instance(
        self,
        instance_id: str,
        timeout: float = 300,
        max_passes: int = 8,
    ) -> Dict[str, Any]:
        """Traced entry for the node-drain verb (docs/operations.md
        "Draining a node without dropping streams")."""
        with tracing.span("launcher.drain", instance=instance_id):
            return self._drain_instance_impl(instance_id, timeout, max_passes)

    def _drain_instance_impl(
        self, instance_id: str, timeout: float, max_passes: int
    ) -> Dict[str, Any]:
        """Repeat migrate passes until the instance reports no queued or
        in-flight work, then declare it drained: every displaced stream
        keeps flowing through the source's claim proxies, new arrivals
        between passes are caught by the next pass, and the instance is
        left idle — safe to stop or kill. Streams still mid-proxy do not
        count as work: the source only forwards tokens for them."""
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        passes: List[Dict[str, Any]] = []
        drained = False
        depth = 0
        for _ in range(max_passes + 1):
            try:
                stats = self._poll_instance_stats(
                    instance_id, min(timeout, 10.0)
                )
            except (StatsFailed, KeyError) as e:
                raise DrainFailed(
                    instance_id, 502, f"stats poll failed: {e}"
                )
            depth = int(stats.get("queue_depth", 0))
            if depth == 0:
                drained = True
                break
            if len(passes) >= max_passes:
                break
            try:
                res = self.migrate_instance(instance_id, timeout=timeout)
            except MigrateFailed as e:
                if e.status == 409 and len(passes) + 1 < max_passes:
                    # a refused pass displaced nothing (the source
                    # resumed or kept its streams): a busy sibling may
                    # free slot/page capacity by the next pass
                    passes.append({"refused": e.detail[:200]})
                    time.sleep(0.2)
                    continue
                raise DrainFailed(
                    instance_id, e.status,
                    f"migrate pass {len(passes) + 1} failed: {e.detail}",
                )
            passes.append({
                "dest_id": res["dest_id"],
                "requests": res["requests"],
                "migrated": res["migrated"],
                "bytes": res["bytes"],
            })
        if not drained:
            raise DrainFailed(
                instance_id, 409,
                f"{depth} request(s) still live after {len(passes)} "
                "migrate pass(es); arrival rate may exceed drain rate — "
                "stop routing new work to this instance and retry",
            )
        result = {
            "instance_id": instance_id,
            "drained": True,
            "passes": passes,
            "migrated": sum(p.get("migrated", 0) for p in passes),
            "bytes": sum(p.get("bytes", 0) for p in passes),
        }
        obj = self.instances[instance_id].get_status()
        obj["drain"] = {
            "passes": len(passes),
            "migrated": result["migrated"],
            "bytes": result["bytes"],
        }
        result["revision"] = self._publish("DRAINED", obj)
        logger.info(
            "drained instance %s: %d pass(es), %d stream(s) migrated "
            "(rev %s)",
            instance_id, len(passes), result["migrated"],
            result["revision"],
        )
        return result

    def _poll_instance_stats(
        self, instance_id: str, timeout: float
    ) -> Dict[str, Any]:
        return self._engine_request(
            instance_id, "GET", "/v1/stats", None, timeout, StatsFailed,
            retries=0,
        )

    def fleet_rollup(
        self, timeout: float = 1.5, ttl_s: float = 1.0
    ) -> Dict[str, Any]:
        """Aggregate every live engine child's GET /v1/stats into the
        node-level SLO/goodput view (the ``fleet`` block of GET
        /v2/vllm/instances) and mirror the aggregates onto the
        fma_launcher_fleet_* gauges. Children are polled concurrently
        with a short per-poll timeout and no retries: an unreachable or
        free-form-options instance degrades to an ``unreachable`` row,
        never an error for the whole read."""
        now = time.monotonic()
        with self._fleet_lock:
            cached = self._fleet_cache
            if cached is not None and now - cached[0] < ttl_s:
                return cached[1]
            ids = list(self.instances)
        # Poll OUTSIDE the lock: a degraded fleet (several unreachable
        # children timing out) must slow only this refresher, not every
        # concurrent /metrics scrape queued behind the lock. Two cold
        # readers may both poll; the second write just wins the cache.
        per_instance: Dict[str, Dict[str, Any]] = {}
        if ids:
            import concurrent.futures as _cf

            with _cf.ThreadPoolExecutor(
                max_workers=min(8, len(ids))
            ) as pool:
                futs = {
                    iid: pool.submit(
                        self._poll_instance_stats, iid, timeout
                    )
                    for iid in ids
                }
            for iid, fut in futs.items():
                try:
                    stats = fut.result()
                except (StatsFailed, KeyError) as e:
                    per_instance[iid] = {
                        "reporting": False,
                        "error": str(e)[:200],
                    }
                    continue
                per_instance[iid] = {"reporting": True, **stats}
        met = violated = 0
        queue_depth = 0
        arrival = 0.0
        goodput = generated = finished = 0
        actuations = 0
        actuations_per_hour = 0.0
        aborted: Dict[str, int] = {}
        preempted = resumed = zd_aborted = zd_migrated = 0
        parked_kv_bytes = 0
        mig: Dict[str, int] = {
            "committed": 0, "resumed_local": 0, "state_loss": 0,
            "requests_out": 0, "requests_in": 0,
            "bytes_out": 0, "bytes_in": 0,
        }
        resident_variants = 0
        variant_hbm_bytes = coresident_saved_bytes = 0
        slo_exemplars: List[Dict[str, Any]] = []
        reporting = 0
        for iid, row in per_instance.items():
            if not row.get("reporting"):
                continue
            reporting += 1
            for ex in row.get("slo_exemplars") or []:
                if isinstance(ex, dict):
                    slo_exemplars.append({"instance": iid, **ex})
            slo = row.get("slo") or {}
            met += int(slo.get("met", 0))
            violated += int(slo.get("violated", 0))
            queue_depth += int(row.get("queue_depth", 0))
            arrival += float(row.get("arrival_rate_rps", 0.0))
            goodput += int(row.get("goodput_tokens", 0))
            generated += int(row.get("generated_tokens", 0))
            finished += int(row.get("finished_requests", 0))
            acts = sum(
                int(v) for v in (row.get("actuations") or {}).values()
            )
            actuations += acts
            uptime = float(row.get("uptime_s", 0.0))
            if uptime > 0:
                actuations_per_hour += acts * 3600.0 / uptime
            for cause, n in (row.get("aborted") or {}).items():
                aborted[cause] = aborted.get(cause, 0) + int(n)
            zd = row.get("zero_drain") or {}
            preempted += int(zd.get("preempted", 0))
            resumed += int(zd.get("resumed", 0))
            zd_aborted += int(zd.get("aborted", 0))
            zd_migrated += int(zd.get("migrated", 0))
            parked_kv_bytes += int(zd.get("parked_kv_bytes", 0))
            mg = row.get("migration") or {}
            for k in mig:
                mig[k] += int(mg.get(k, 0))
            res = row.get("residents") or {}
            resident_variants += 1 + len(res.get("attached") or [])
            variant_hbm_bytes += int(res.get("variant_hbm_bytes", 0))
            coresident_saved_bytes += int(res.get("saved_bytes", 0))
        judged = met + violated
        attainment = round(met / judged, 6) if judged else None
        fleet = {
            "instances_total": len(ids),
            "instances_reporting": reporting,
            "queue_depth": queue_depth,
            "arrival_rate_rps": round(arrival, 6),
            "slo_requests_met": met,
            "slo_requests_violated": violated,
            "slo_attainment": attainment,
            "finished_requests": finished,
            "generated_tokens": generated,
            "goodput_tokens": goodput,
            "actuations": actuations,
            "actuations_per_hour": round(actuations_per_hour, 3),
            "aborted": aborted,
            # zero-drain preemption rollup (engine /v1/stats zero_drain):
            # fleet-wide "did actuation drop any stream" in one read
            "zero_drain": {
                "preempted": preempted,
                "resumed": resumed,
                "aborted": zd_aborted,
                "migrated": zd_migrated,
                "parked_kv_bytes": parked_kv_bytes,
            },
            # live-migration rollup (engine /v1/stats migration):
            # fleet-wide "did any handoff lose state" in one read
            "migration": mig,
            # co-residency rollup (engine /v1/stats residents): how many
            # variants are device-resident fleet-wide, their delta HBM
            # footprint, and what sharing the base tensors saved
            "residents": {
                "resident_variants": resident_variants,
                "variant_hbm_bytes": variant_hbm_bytes,
                "coresident_saved_bytes": coresident_saved_bytes,
            },
            # SLO-violation exemplars lifted from every reporting child
            # (engine /v1/stats slo_exemplars), each tagged with the
            # instance it came from so an operator can pull the trace
            # via that child's GET /v1/traces?trace_id=
            "slo_exemplars": slo_exemplars[-16:],
            "per_instance": per_instance,
        }
        LAUNCHER_FLEET_INSTANCES.labels(state="reporting").set(reporting)
        LAUNCHER_FLEET_INSTANCES.labels(state="unreachable").set(
            len(ids) - reporting
        )
        LAUNCHER_FLEET_QUEUE_DEPTH.set(queue_depth)
        LAUNCHER_FLEET_ARRIVAL_RATE.set(arrival)
        LAUNCHER_FLEET_SLO_ATTAINMENT.set(
            attainment if attainment is not None else 1.0
        )
        LAUNCHER_FLEET_GOODPUT_TOKENS.set(goodput)
        LAUNCHER_FLEET_ACTUATIONS_PER_HOUR.set(actuations_per_hour)
        LAUNCHER_FLEET_RESIDENT_VARIANTS.set(resident_variants)
        LAUNCHER_FLEET_CORESIDENT_SAVED_BYTES.set(coresident_saved_bytes)
        with self._fleet_lock:
            self._fleet_cache = (time.monotonic(), fleet)
        return fleet

    def stop_all_instances(self, timeout: float = 10) -> Dict[str, Any]:
        stopped = []
        for iid in list(self.instances):
            self.stop_instance(iid, timeout=timeout)
            stopped.append(iid)
        return {"status": "all_stopped", "stopped_instances": stopped}

    def get_instance_status(self, instance_id: str) -> Dict[str, Any]:
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        return self.instances[instance_id].get_status()

    def get_all_instances_status(
        self, include_fleet: bool = False
    ) -> Dict[str, Any]:
        statuses = []
        running = 0
        for instance in self.instances.values():
            st = instance.get_status()
            statuses.append(st)
            if st["status"] == STATUS_RUNNING:
                running += 1
        out: Dict[str, Any] = {
            "total_instances": len(statuses),
            "running_instances": running,
            "instances": statuses,
            # node-local actuation state a multi-model scheduler reads in
            # one call: who holds which chips, what each holder serves,
            # what's staged (prefetch hints), and each holder's tiered
            # pool shape (pooled models, deduped residency, disk tier)
            "ledger": {
                "models": self.ledger.models(),
                "prefetched": self.ledger.prefetched(),
                "pools": self.ledger.pools(),
                # per-holder transfer mode of the last swap ("int8"/"fp8"
                # when the holder actuates compressed, docs/perf.md)
                "quant": self.ledger.quants(),
                # per-holder co-resident variant sets (docs/launcher.md
                # "The resident-set ledger"): the routes a scheduler can
                # take WITHOUT any actuation, next to what each costs in
                # variant HBM and what the shared base saves
                "residents": self.ledger.residents(),
            },
        }
        if include_fleet:
            # blocking child polls: only REST's executor-threaded GET
            # /v2/vllm/instances asks for it — in-process callers on the
            # event loop (the notifier's lister) must not
            try:
                out["fleet"] = self.fleet_rollup()
            except Exception as e:  # noqa: BLE001 — rollup never fails the read
                logger.warning("fleet rollup failed: %s", e)
                out["fleet"] = {"error": str(e)[:200]}
            # cost-oracle rollup (docs/launcher.md "The costs block"):
            # each reporting child's /v1/stats already carries its
            # bandwidth EWMAs + prediction accuracy — lift them into the
            # ledger so ONE detailed read serves the scheduler's whole
            # input: demand (fleet), state (ledger), cost (this block),
            # all from the same poll cycle
            per = (out["fleet"] or {}).get("per_instance") or {}
            out["ledger"]["costs"] = {
                iid: row.get("costs")
                for iid, row in per.items()
                if row.get("reporting") and row.get("costs") is not None
            }
        return out

    def list_instances(self) -> List[str]:
        return list(self.instances.keys())

    def get_instance_log_bytes(
        self, instance_id: str, start: int = 0, end: Optional[int] = None
    ):
        if instance_id not in self.instances:
            raise KeyError(instance_id)
        return self.instances[instance_id].get_log_bytes(start, end)
