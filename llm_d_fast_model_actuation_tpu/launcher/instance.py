"""One engine instance: a forked child process running the engine server.

Mirrors the reference's `VllmInstance` semantics (launcher.py:157-340):
status vocabulary (started / already_running / running / stopped /
not_running / terminated), per-instance log file dup2'd over the child's
stdout/stderr, graceful SIGTERM then process-group SIGKILL, and **sentinel
crash detection**: the child's `multiprocessing` sentinel fd is registered on
the event loop, so process death becomes a callback with zero polling.

TPU deltas: chip IDs translate to TPU_VISIBLE_DEVICES / process-bounds env
(not CUDA_VISIBLE_DEVICES), and the fork inherits the preloaded JAX modules
plus a shared persistent XLA compilation-cache dir (cold-start killer on TPU,
where compilation dominates).
"""

from __future__ import annotations

import logging
import multiprocessing
import os
import shlex
import signal
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from .chiptranslator import ChipTranslator

logger = logging.getLogger(__name__)

MAX_LOG_RESPONSE_BYTES = 1 << 20  # 1 MiB per ranged-log response

#: serializes the FMA_TRACEPARENT stamp -> fork -> restore window in
#: start(): os.environ is process-global, and concurrent creates (REST
#: handlers run manager verbs on the executor pool) would otherwise
#: cross-wire children's trace parents or leave a stale one behind
_FORK_ENV_LOCK = threading.Lock()


def replace_model_option(
    options: str, model: str, checkpoint_dir: str = ""
) -> str:
    """Rewrite the ``--model`` (and ``--checkpoint-dir``) values in an
    engine options string. After a hot-swap the child serves a different
    model than it was forked with; the stored config must describe reality
    (status responses, and any future restart of the instance) — which
    means the OLD model's checkpoint dir must never survive attached to
    the new model's name (a restart would load shape-mismatched weights)."""
    parts = shlex.split(options or "")
    out: List[str] = []
    replaced = False
    i = 0
    while i < len(parts):
        p = parts[i]
        if p == "--model" and i + 1 < len(parts):
            out += ["--model", model]
            i += 2
            replaced = True
        elif p.startswith("--model="):
            out.append(f"--model={model}")
            i += 1
            replaced = True
        elif p == "--checkpoint-dir" and i + 1 < len(parts):
            i += 2  # dropped; re-added below if the swap supplied one
        elif p.startswith("--checkpoint-dir="):
            i += 1
        else:
            out.append(p)
            i += 1
    if not replaced:
        out = ["--model", model] + out
    if checkpoint_dir:
        out += ["--checkpoint-dir", checkpoint_dir]
    return shlex.join(out)


class InvalidInstanceConfig(Exception):
    """The instance config is semantically invalid (e.g. unknown chip ID)."""


class HalfMade(Exception):
    """Something other than start() was the first op on an instance."""

    def __init__(self, instance_id: str) -> None:
        super().__init__(instance_id)
        self.instance_id = instance_id


class LogRangeNotAvailable(Exception):
    def __init__(self, requested: int, total: int) -> None:
        super().__init__(f"start {requested} beyond total {total}")
        self.requested = requested
        self.total = total


@dataclass
class InstanceConfig:
    """Wire config of one instance (reference VllmConfig, launcher.py:64-68).

    Serialized with the reference's field names (`options`, `gpu_uuids`,
    `env_vars`, `annotations`) so the reference's Go launcher client talks to
    this launcher unchanged; `chip_ids` is accepted as an input alias."""

    options: str = ""
    chip_ids: Optional[List[str]] = None
    env_vars: Optional[Dict[str, str]] = None
    annotations: Optional[Dict[str, str]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"options": self.options}
        if self.chip_ids is not None:
            d["gpu_uuids"] = list(self.chip_ids)
        if self.env_vars is not None:
            d["env_vars"] = dict(self.env_vars)
        if self.annotations is not None:
            d["annotations"] = dict(self.annotations)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "InstanceConfig":
        if "options" not in d:
            raise ValueError("instance config requires 'options'")
        chips = d.get("chip_ids", d.get("gpu_uuids"))
        return cls(
            options=str(d["options"]),
            chip_ids=None if chips is None else [str(c) for c in chips],
            env_vars=None if d.get("env_vars") is None else dict(d["env_vars"]),
            annotations=None
            if d.get("annotations") is None
            else dict(d["annotations"]),
        )


def _close_inherited_sockets() -> None:
    """Close inherited *socket* fds in the child (keep pipes, incl. the
    sentinel) — the reference's fix for wedged client connections inherited
    across fork (launcher.py:808-832, issue #550)."""
    import stat

    for fd in range(3, 1024):
        try:
            mode = os.fstat(fd).st_mode
        except OSError:
            continue
        if stat.S_ISSOCK(mode):
            try:
                os.close(fd)
            except OSError:
                pass


def engine_kickoff(config: InstanceConfig, log_path: str) -> None:
    """Child-process body: new process group, stdio -> log file, env, then
    the engine server (modules already imported pre-fork = preloading)."""
    os.setpgrp()
    _close_inherited_sockets()
    fd = os.open(log_path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    if fd > 2:
        os.close(fd)
    for k, v in (config.env_vars or {}).items():
        os.environ[k] = str(v)
    # per-instance FMA_FAULTS must win over (latched) launcher-level state
    from ..utils import faults, tracing

    faults.load_env(force=True)
    # forked-child hygiene: drop the ring-buffer copy inherited from the
    # launcher and re-read FMA_TRACING/FMA_TRACE_BUFFER (per-instance
    # env_vars win); FMA_TRACEPARENT stays for the engine.start span
    tracing.reset_after_fork()
    # same hygiene for prometheus: the fork duplicated the launcher's
    # registered fma_launcher_rpc_seconds (frozen at fork time) into this
    # child's default registry — without this the engine's GET /metrics
    # would export stale launcher-family samples (docs/metrics.md pins
    # the family to the launcher port)
    try:
        from prometheus_client import REGISTRY

        from .manager import LAUNCHER_RPC_SECONDS

        REGISTRY.unregister(LAUNCHER_RPC_SECONDS)
    except (ImportError, KeyError):
        pass
    from ..engine.server import parse_engine_options, run_server

    args = parse_engine_options(config.options)
    run_server(args)


class EngineInstance:
    def __init__(
        self,
        instance_id: str,
        config: InstanceConfig,
        translator: ChipTranslator,
        log_dir: str = "",
        kickoff=engine_kickoff,
    ) -> None:
        # Translate chip IDs to device-pinning env at construction time
        # (the reference's CUDA_VISIBLE_DEVICES injection, launcher.py:175-191).
        if config.chip_ids:
            try:
                env = translator.env_for(config.chip_ids)
            except KeyError as e:
                raise InvalidInstanceConfig(f"unknown chip id {e.args[0]!r}")
            config.env_vars = {**(config.env_vars or {}), **env}
            logger.info(
                "instance %s: chips %s -> %s",
                instance_id,
                config.chip_ids,
                env["TPU_VISIBLE_DEVICES"],
            )
        self.instance_id = instance_id
        self.config = config
        self.process: Optional[multiprocessing.Process] = None
        self.last_revision: Optional[int] = None
        self._kickoff = kickoff
        self._sentinel_active = False
        self._on_exit_callback = None
        self._log_file_path = os.path.join(
            log_dir or "/tmp", f"launcher-{os.getpid()}-engine-{instance_id}.log"
        )

    # -- state rendering -----------------------------------------------------

    def _make_state(self, status: str) -> Dict[str, Any]:
        return {
            "status": status,
            "instance_id": self.instance_id,
            "revision": self.last_revision,
            # the child's pid (None pre-start): fault drills and the
            # supervisor e2e need a real process to signal
            "pid": self.process.pid if self.process is not None else None,
            **self.config.to_dict(),
        }

    # -- lifecycle -----------------------------------------------------------

    def start(
        self, fresh_log: bool = True, restart: bool = False
    ) -> Dict[str, Any]:
        if self.process and self.process.is_alive():
            return self._make_state("already_running")
        if fresh_log or not os.path.exists(self._log_file_path):
            open(self._log_file_path, "wb").close()
        else:
            # supervised restart: append below the crash forensics (the
            # kickoff opens O_APPEND), with a marker separating the lives
            with open(self._log_file_path, "ab") as f:
                f.write(b"\n--- supervised restart ---\n")
        self.process = multiprocessing.get_context("fork").Process(
            target=self._kickoff, args=(self.config, self._log_file_path)
        )
        # Cross-fork trace propagation: stamp the caller's span context
        # (the launcher's create/restart span) into the env the fork
        # inherits, so the child's engine.start span joins the trace
        # (utils/tracing.py; restored right after the fork — the env of a
        # long-lived launcher must not carry a stale parent). A
        # supervised restart additionally stamps FMA_RESTARTED so the
        # child's flight recorder (utils/costs.py) attributes its initial
        # cold build to restart churn, not client-driven actuation.
        from ..utils import tracing

        tp = tracing.current_traceparent()
        with _FORK_ENV_LOCK:
            prev_tp = os.environ.get(tracing.TRACEPARENT_ENV)
            prev_rs = os.environ.get("FMA_RESTARTED")
            if tp:
                os.environ[tracing.TRACEPARENT_ENV] = tp
            if restart:
                os.environ["FMA_RESTARTED"] = "1"
            try:
                self.process.start()
            finally:
                if tp:
                    if prev_tp is None:
                        os.environ.pop(tracing.TRACEPARENT_ENV, None)
                    else:
                        os.environ[tracing.TRACEPARENT_ENV] = prev_tp
                if restart:
                    if prev_rs is None:
                        os.environ.pop("FMA_RESTARTED", None)
                    else:
                        os.environ["FMA_RESTARTED"] = prev_rs
        return self._make_state("started")

    def stop(self, timeout: float = 10) -> Dict[str, Any]:
        if self.process is None:
            raise HalfMade(self.instance_id)
        if not self.process.is_alive():
            self._cleanup_log_file()
            return self._make_state("not_running")
        self.process.terminate()  # graceful: SIGTERM to the server
        self.process.join(timeout=timeout)
        if self.process.is_alive():
            try:
                os.killpg(self.process.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            self.process.join()
        self._cleanup_log_file()
        return self._make_state("terminated")

    def get_status(self) -> Dict[str, Any]:
        if self.process is None:
            raise HalfMade(self.instance_id)
        return self._make_state(
            "running" if self.process.is_alive() else "stopped"
        )

    # -- crash detection -----------------------------------------------------

    def start_sentinel_watcher(self, on_exit_callback) -> None:
        """Register the child's sentinel fd on the running event loop; the
        kernel makes it readable when the child dies."""
        import asyncio

        if self.process is None:
            raise HalfMade(self.instance_id)
        self._on_exit_callback = on_exit_callback
        loop = asyncio.get_running_loop()
        loop.add_reader(self.process.sentinel, self._on_sentinel_exit)
        self._sentinel_active = True

    def _on_sentinel_exit(self) -> None:
        import asyncio

        loop = asyncio.get_running_loop()
        loop.remove_reader(self.process.sentinel)
        self._sentinel_active = False
        # Reap so exitcode is populated. The sentinel can become readable a
        # beat before the child is waitable, so a zero-timeout join can miss;
        # a short blocking join is effectively instant here.
        self.process.join(timeout=2)
        if self._on_exit_callback:
            self._on_exit_callback(self.instance_id, self.process.exitcode)

    def cancel_sentinel_watcher(self) -> None:
        import asyncio

        if self._sentinel_active and self.process is not None:
            try:
                asyncio.get_running_loop().remove_reader(self.process.sentinel)
            except RuntimeError:
                pass
            self._sentinel_active = False

    # -- logs ----------------------------------------------------------------

    def _cleanup_log_file(self) -> None:
        try:
            os.unlink(self._log_file_path)
        except FileNotFoundError:
            pass

    def get_log_bytes(
        self, start: int = 0, end: Optional[int] = None
    ) -> tuple:
        """(content, total_length) for [start, end] (inclusive), capped at
        MAX_LOG_RESPONSE_BYTES. Raises LogRangeNotAvailable if start >= total."""
        try:
            total = os.path.getsize(self._log_file_path)
        except FileNotFoundError:
            total = 0
        if start >= total:
            raise LogRangeNotAvailable(start, total)
        if end is None:
            read_end = min(start + MAX_LOG_RESPONSE_BYTES - 1, total - 1)
        else:
            read_end = min(end, total - 1)
        with open(self._log_file_path, "rb") as f:
            f.seek(start)
            data = f.read(read_end - start + 1)
        return data, total
