"""Chip identity translation: chip IDs <-> local indices / env injection.

TPU edition of the reference's `gputranslator.py` (3-tier mode selection,
docs/launcher.md:656-696):

  1. **chip-map mock** — a chip-map ConfigMap-shaped source (file or dict)
     keyed by NODE_NAME: the shared source of truth for hardware-less e2e;
  2. **naive mock** — N synthetic chips in a row topology;
  3. **real** — enumerate local TPU chips via the native telemetry shim
     (``native/tpuinfo``, ctypes) with a /dev + sysfs fallback.

Unlike the GPU original (flat UUID->index), the translator exposes the host
*topology* so placement can demand ICI-contiguous sub-slices.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional, Sequence

from ..parallel.topology import ChipMap, HostTopology

logger = logging.getLogger(__name__)


class ChipTranslator:
    def __init__(self, host: HostTopology, mode: str) -> None:
        self._host = host
        self.mode = mode

    # -- constructors --------------------------------------------------------

    @classmethod
    def create(
        cls,
        mock_chips: bool = False,
        mock_chip_count: int = 8,
        mock_topology: str = "",
        chip_map_path: Optional[str] = None,
        node_name: Optional[str] = None,
    ) -> "ChipTranslator":
        """Mode selection, highest priority first: chip-map mock -> naive
        mock -> real hardware."""
        if mock_chips:
            node = node_name or os.environ.get("NODE_NAME", "")
            path = chip_map_path or os.environ.get("CHIP_MAP_PATH", "")
            if node and path and os.path.exists(path):
                with open(path) as f:
                    data = json.load(f)
                cm = ChipMap.parse(data)
                host = cm.host(node)
                if host is not None:
                    logger.info("chip-map mock: node %s, %s chips", node, len(host.chips))
                    return cls(host, mode="chip-map-mock")
                logger.warning("node %s not in chip map %s; naive fallback", node, path)
            topo = mock_topology or _default_topology(mock_chip_count)
            host = HostTopology.make(topo, node=node or "mock")
            logger.info("naive mock: %s chips (topology %s)", len(host.chips), topo)
            return cls(host, mode="naive-mock")
        return cls(_enumerate_real(), mode="real")

    # -- queries -------------------------------------------------------------

    @property
    def host(self) -> HostTopology:
        return self._host

    def chip_ids(self) -> List[str]:
        return [c.chip_id for c in self._host.chips]

    def id_to_index(self, chip_id: str) -> int:
        info = self._host.by_id().get(chip_id)
        if info is None:
            raise KeyError(f"unknown chip id {chip_id!r}")
        return info.index

    def env_for(self, chip_ids: Sequence[str]) -> Dict[str, str]:
        """Env vars pinning an engine process to `chip_ids`."""
        return self._host.visible_devices_env(chip_ids)


def _default_topology(n: int) -> str:
    if n >= 8 and n % 4 == 0:
        return f"{n // 4}x4"
    return str(n)


def _enumerate_real() -> HostTopology:
    """Real-hardware enumeration: native shim first, sysfs/devfs fallback."""
    try:
        from ..native import tpuinfo

        chips = tpuinfo.enumerate_chips()
        if chips:
            topo = tpuinfo.host_topology() or _default_topology(len(chips))
            host = HostTopology.make(topo, node=os.environ.get("NODE_NAME", "local"))
            # keep shim-reported IDs
            from ..parallel.topology import ChipInfo

            host.chips = [
                ChipInfo(chip_id=c["chip_id"], index=c["index"], coords=tuple(c.get("coords", ())))
                for c in chips
            ]
            return host
    except Exception as e:  # shim not built / not on a TPU host
        logger.debug("native tpuinfo unavailable: %s", e)
    # /dev/accel* fallback (TPU VMs expose one accel device per chip)
    accels = sorted(
        int(name[5:])
        for name in os.listdir("/dev")
        if name.startswith("accel") and name[5:].isdigit()
    ) if os.path.isdir("/dev") else []
    if accels:
        host = HostTopology.make(_default_topology(len(accels)), node="local")
        return host
    raise RuntimeError(
        "no TPU chips found (native shim unavailable, no /dev/accel*); "
        "use a mock backend (launcher: --mock-chips, requester: --backend "
        "static/env) for hardware-less operation"
    )
