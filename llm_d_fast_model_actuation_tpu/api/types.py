"""Typed API objects (the CRD equivalents), TPU edition.

Parity map:
  InferenceServerConfig  -> api/fma/v1alpha1/inferenceserverconfig_types.go:24-107
  LauncherConfig         -> api/fma/v1alpha1/launcherconfig_types.go:26-101
  LauncherPopulationPolicy -> api/fma/v1alpha1/launcherpopulationpolicy_types.go:25-143

TPU-first deltas from the reference:
  * ``EngineServerConfig`` (the reference's ``ModelServerConfig``) grows an
    :class:`AcceleratorSpec` with chip count **and** slice topology — TPU
    placement is topology-aware (a "2x2" sub-slice is not any 4 chips), while
    the GPU reference only knows a flat UUID list.
  * Quantities are plain ints/strings; the k8s ``resource.Quantity`` grammar is
    handled by :func:`parse_quantity`.

Objects serialize to/from kube-shaped dicts (camelCase JSON field names match
the reference CRDs) so manifests written for the reference port verbatim.
"""

from __future__ import annotations

import dataclasses
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# -- k8s resource.Quantity ---------------------------------------------------

_QUANTITY_RE = re.compile(r"^([+-]?[0-9.]+)([EPTGMk]i?|[munpf]|[eE][+-]?[0-9]+)?$")
_SUFFIX = {
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
    "m": 10**-3, "u": 10**-6, "n": 10**-9, "p": 10**-12, "f": 10**-15,
}


def parse_quantity(q: "int | float | str") -> float:
    """Parse a Kubernetes resource quantity ("4", "16Gi", "500m") to a float."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {q!r}")
    base, suffix = m.groups()
    mult = 1.0
    if suffix:
        if suffix[0] in "eE" and suffix not in _SUFFIX and len(suffix) > 1:
            mult = 10 ** int(suffix[1:])
        else:
            mult = _SUFFIX[suffix]
    return float(base) * mult


# -- metadata ----------------------------------------------------------------


@dataclass
class ObjectMeta:
    """The subset of kube ObjectMeta the framework uses."""

    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    generation: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[Dict[str, Any]] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None
    creation_timestamp: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name}
        if self.namespace:
            d["namespace"] = self.namespace
        if self.uid:
            d["uid"] = self.uid
        if self.resource_version:
            d["resourceVersion"] = self.resource_version
        if self.generation:
            d["generation"] = self.generation
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.finalizers:
            d["finalizers"] = list(self.finalizers)
        if self.owner_references:
            d["ownerReferences"] = list(self.owner_references)
        if self.deletion_timestamp is not None:
            d["deletionTimestamp"] = self.deletion_timestamp
        if self.creation_timestamp is not None:
            d["creationTimestamp"] = self.creation_timestamp
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", ""),
            uid=d.get("uid", ""),
            resource_version=str(d.get("resourceVersion", "") or ""),
            generation=int(d.get("generation", 0) or 0),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            finalizers=list(d.get("finalizers") or []),
            owner_references=list(d.get("ownerReferences") or []),
            deletion_timestamp=d.get("deletionTimestamp"),
            creation_timestamp=d.get("creationTimestamp"),
        )


@dataclass
class Status:
    """Common CR status: reference *_types.go `{ObservedGeneration, Errors}`."""

    observed_generation: int = 0
    errors: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.observed_generation:
            d["observedGeneration"] = self.observed_generation
        if self.errors:
            d["errors"] = list(self.errors)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Status":
        return cls(
            observed_generation=int(d.get("observedGeneration", 0) or 0),
            errors=list(d.get("errors") or []),
        )


# -- TPU topology ------------------------------------------------------------


@dataclass(frozen=True)
class SliceTopology:
    """A TPU slice topology, e.g. 2x4 (v5e-8 host) or 4x4x4 (v4 cube).

    The reference's accelerator model is a flat GPU-UUID list; on TPU the
    physical mesh shape governs which chip subsets are ICI-connected, so the
    topology is part of the placement contract (SURVEY.md §5, §7).
    """

    dims: tuple

    @classmethod
    def parse(cls, s: str) -> "SliceTopology":
        if not s:
            raise ValueError("empty topology")
        try:
            dims = tuple(int(p) for p in s.lower().split("x"))
        except ValueError as e:
            raise ValueError(f"invalid topology {s!r}") from e
        if not dims or any(d <= 0 for d in dims):
            raise ValueError(f"invalid topology {s!r}")
        return cls(dims)

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def __str__(self) -> str:
        return "x".join(str(d) for d in self.dims)

    def contains(self, other: "SliceTopology") -> bool:
        """Whether a sub-slice of shape `other` fits inside this slice."""
        if len(other.dims) > len(self.dims):
            return False
        pad = (1,) * (len(self.dims) - len(other.dims))
        od = pad + tuple(sorted(other.dims))
        sd = tuple(sorted(self.dims))
        return all(o <= s for o, s in zip(od, sd))


@dataclass
class AcceleratorSpec:
    """TPU accelerator requirements of one engine instance."""

    #: Number of chips (tensor-parallel degree for the engine). For a
    #: multi-host slice this is chips PER HOST.
    chips: int = 1
    #: Required sub-slice topology, e.g. "2x2"; empty = any `chips` chips on
    #: one host. With hosts > 1 this is the GLOBAL slice topology (e.g.
    #: "4x4" over two 2x4 hosts).
    topology: str = ""
    #: Hosts the slice spans. 1 = single-host (the reference's only case);
    #: > 1 actuates a gang of requester/provider pairs whose engine
    #: processes form one jax.distributed job (parallel/multihost.py).
    hosts: int = 1
    #: Whether the ISC explicitly declared an accelerator spec. Only then is
    #: placement validated against it (an absent spec accepts whatever the
    #: scheduler assigned, matching the reference's behavior).
    specified: bool = False

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"chips": self.chips}
        if self.topology:
            d["topology"] = self.topology
        if self.hosts != 1:
            d["hosts"] = self.hosts
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AcceleratorSpec":
        return cls(
            chips=int(d.get("chips", 1) or 1),
            topology=d.get("topology", ""),
            hosts=int(d.get("hosts", 1) or 1),
            specified=bool(d),
        )


# -- InferenceServerConfig ---------------------------------------------------


@dataclass
class EngineServerConfig:
    """One engine instance's config (the reference's ModelServerConfig,
    inferenceserverconfig_types.go:35-62).

    ``options`` is the engine CLI option string passed through verbatim
    (e.g. ``--model meta-llama/Llama-3-8B --tensor-parallel-size 8``);
    ``labels``/``annotations`` are routing metadata stamped on the providing
    Pod only while bound and serving (deferred-routing invariant).
    """

    port: int = 8000
    options: str = ""
    env_vars: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    accelerator: AcceleratorSpec = field(default_factory=AcceleratorSpec)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"port": self.port}
        if self.options:
            d["options"] = self.options
        if self.env_vars:
            d["env_vars"] = dict(self.env_vars)
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        acc = self.accelerator.to_dict()
        if acc != {"chips": 1}:
            d["accelerator"] = acc
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EngineServerConfig":
        return cls(
            port=int(d.get("port", 8000) or 8000),
            options=d.get("options", ""),
            env_vars=dict(d.get("env_vars") or {}),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            accelerator=AcceleratorSpec.from_dict(d.get("accelerator") or {}),
        )


@dataclass
class InferenceServerConfigSpec:
    engine_server_config: EngineServerConfig = field(default_factory=EngineServerConfig)
    launcher_config_name: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "modelServerConfig": self.engine_server_config.to_dict(),
            "launcherConfigName": self.launcher_config_name,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "InferenceServerConfigSpec":
        return cls(
            engine_server_config=EngineServerConfig.from_dict(
                d.get("modelServerConfig") or {}
            ),
            launcher_config_name=d.get("launcherConfigName", ""),
        )


@dataclass
class InferenceServerConfig:
    """Declares one engine instance config; shortName `isc`."""

    KIND = "InferenceServerConfig"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: InferenceServerConfigSpec = field(default_factory=InferenceServerConfigSpec)
    status: Status = field(default_factory=Status)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "fma.llm-d.ai/v1alpha1",
            "kind": self.KIND,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "InferenceServerConfig":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=InferenceServerConfigSpec.from_dict(d.get("spec") or {}),
            status=Status.from_dict(d.get("status") or {}),
        )


# -- LauncherConfig ----------------------------------------------------------


@dataclass
class PodTemplate:
    """EmbeddedPodTemplateSpec (launcherconfig_types.go:26-44): metadata
    labels/annotations + a Pod spec dict (kept as a plain dict — the template
    builder manipulates it structurally)."""

    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    spec: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        meta: Dict[str, Any] = {}
        if self.labels:
            meta["labels"] = dict(self.labels)
        if self.annotations:
            meta["annotations"] = dict(self.annotations)
        return {"metadata": meta, "spec": self.spec}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PodTemplate":
        meta = d.get("metadata") or {}
        return cls(
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
            spec=dict(d.get("spec") or {}),
        )


@dataclass
class LauncherConfigSpec:
    pod_template: PodTemplate = field(default_factory=PodTemplate)
    max_instances: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return {
            "podTemplate": self.pod_template.to_dict(),
            "maxInstances": self.max_instances,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LauncherConfigSpec":
        return cls(
            pod_template=PodTemplate.from_dict(d.get("podTemplate") or {}),
            max_instances=int(d.get("maxInstances", 1) or 1),
        )


@dataclass
class LauncherConfig:
    """Pod template for launcher Pods + per-launcher instance cap;
    shortName `lcfg`."""

    KIND = "LauncherConfig"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LauncherConfigSpec = field(default_factory=LauncherConfigSpec)
    status: Status = field(default_factory=Status)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "fma.llm-d.ai/v1alpha1",
            "kind": self.KIND,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LauncherConfig":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=LauncherConfigSpec.from_dict(d.get("spec") or {}),
            status=Status.from_dict(d.get("status") or {}),
        )


# -- LauncherPopulationPolicy ------------------------------------------------


@dataclass
class ResourceRange:
    """Allocatable-resource min/max (launcherpopulationpolicy_types.go:103-113)."""

    min: Optional[str] = None
    max: Optional[str] = None

    def matches(self, value: "int | float | str") -> bool:
        v = parse_quantity(value)
        if self.min is not None and v < parse_quantity(self.min):
            return False
        if self.max is not None and v > parse_quantity(self.max):
            return False
        return True

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.min is not None:
            d["min"] = self.min
        if self.max is not None:
            d["max"] = self.max
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceRange":
        return cls(
            min=None if d.get("min") is None else str(d["min"]),
            max=None if d.get("max") is None else str(d["max"]),
        )


@dataclass
class EnhancedNodeSelector:
    """Label selector AND allocatable-resource ranges
    (launcherpopulationpolicy_types.go:88-113)."""

    #: matchLabels-style exact-equality selector (the subset the framework
    #: evaluates; matchExpressions can be added without API change).
    match_labels: Dict[str, str] = field(default_factory=dict)
    allocatable_resources: Dict[str, ResourceRange] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"labelSelector": {"matchLabels": dict(self.match_labels)}}
        if self.allocatable_resources:
            d["allocatableResources"] = {
                k: v.to_dict() for k, v in self.allocatable_resources.items()
            }
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EnhancedNodeSelector":
        sel = d.get("labelSelector") or {}
        return cls(
            match_labels=dict(sel.get("matchLabels") or {}),
            allocatable_resources={
                k: ResourceRange.from_dict(v or {})
                for k, v in (d.get("allocatableResources") or {}).items()
            },
        )


@dataclass
class CountForLauncher:
    launcher_config_name: str = ""
    launcher_count: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "launcherConfigName": self.launcher_config_name,
            "launcherCount": self.launcher_count,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CountForLauncher":
        return cls(
            launcher_config_name=d.get("launcherConfigName", ""),
            launcher_count=int(d.get("launcherCount", 0) or 0),
        )


@dataclass
class LauncherPopulationPolicySpec:
    enhanced_node_selector: EnhancedNodeSelector = field(
        default_factory=EnhancedNodeSelector
    )
    count_for_launcher: List[CountForLauncher] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "enhancedNodeSelector": self.enhanced_node_selector.to_dict(),
            "countForLauncher": [c.to_dict() for c in self.count_for_launcher],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LauncherPopulationPolicySpec":
        return cls(
            enhanced_node_selector=EnhancedNodeSelector.from_dict(
                d.get("enhancedNodeSelector") or {}
            ),
            count_for_launcher=[
                CountForLauncher.from_dict(c)
                for c in (d.get("countForLauncher") or [])
            ],
        )


@dataclass
class LauncherPopulationPolicy:
    """Proactive launcher population policy; shortName `lpp`. All LPPs jointly
    define (Node, LauncherConfig) -> max(count); effective desired =
    max(policy, demand) (docs/dual-pods.md:151-174)."""

    KIND = "LauncherPopulationPolicy"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LauncherPopulationPolicySpec = field(
        default_factory=LauncherPopulationPolicySpec
    )
    status: Status = field(default_factory=Status)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "fma.llm-d.ai/v1alpha1",
            "kind": self.KIND,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LauncherPopulationPolicy":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=LauncherPopulationPolicySpec.from_dict(d.get("spec") or {}),
            status=Status.from_dict(d.get("status") or {}),
        )


# -- wire types --------------------------------------------------------------


@dataclass
class ServerRequestingPodStatus:
    """JSON value of the status annotation (pkg/api/interface.go:58-66)."""

    errors: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"Errors": list(self.errors)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServerRequestingPodStatus":
        return cls(errors=list(d.get("Errors") or []))


@dataclass
class SleepState:
    """GET /is_sleeping response (pkg/api/interface.go:131-135)."""

    is_sleeping: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {"is_sleeping": self.is_sleeping}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SleepState":
        return cls(is_sleeping=bool(d.get("is_sleeping")))


def asdict_shallow(obj: Any) -> Dict[str, Any]:
    return dataclasses.asdict(obj)
