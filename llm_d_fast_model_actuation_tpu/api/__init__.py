"""Public API contract of the dual-pods technique, TPU edition.

The annotation/label vocabulary is kept wire-compatible with the reference
(`pkg/api/interface.go`, `pkg/controller/common/interface.go`) so that an
existing llm-d FMA deployment can switch engines without re-teaching its
ecosystem (EPP routing, autoscalers, benchmarks). TPU-specific additions use
the same domain with new suffixes.
"""

from .constants import *  # noqa: F401,F403
from .types import (  # noqa: F401
    AcceleratorSpec,
    EngineServerConfig,
    InferenceServerConfig,
    InferenceServerConfigSpec,
    LauncherConfig,
    LauncherConfigSpec,
    LauncherPopulationPolicy,
    LauncherPopulationPolicySpec,
    ObjectMeta,
    ServerRequestingPodStatus,
    SleepState,
    SliceTopology,
    Status,
)
