"""Requester-stub SPI: the HTTP contract served inside the requesting Pod.

Parity with reference `pkg/spi/interface.go:29-61`. The dual-pods controller
is the client; the requester stub (``fma_tpu.requester``) is the server.
"""

#: GET -> 200 with a JSON array of strings, each identifying one TPU chip in
#: a way appropriate for the software accessing the chips (we use stable chip
#: IDs of the form "tpu-<serial-or-pci>").
ACCELERATOR_QUERY_PATH = "/v1/dual-pods/accelerators"

#: GET -> JSON object {chip_id: bytes_of_HBM_in_use}.
ACCELERATOR_MEMORY_QUERY_PATH = "/v1/dual-pods/accelerator-memory-usage"

#: POST -> set readiness true (relayed to the kubelet via the probes server).
BECOME_READY_PATH = "/v1/become-ready"

#: POST -> set readiness false.
BECOME_UNREADY_PATH = "/v1/become-unready"

#: GET -> 200/503 from the readiness bool (kubelet readiness probe target).
READY_PATH = "/ready"

#: POST text/plain chunk of the engine's log, with query param
#: :data:`LOG_START_POS_PARAM` = 0-based start offset; the requester keeps
#: only new content (dedups overlaps), 400 if startPos is beyond the end.
SET_LOG_PATH = "/v1/set-log"

LOG_START_POS_PARAM = "startPos"
