"""Annotation / label / port contract between users, controllers, and agents.

Parity map (reference `file:line` -> here):
  pkg/api/interface.go:47-135      -> user-facing annotations and FYI labels
  pkg/controller/common/interface.go:19-42 -> controller-internal shared keys
  pkg/spi/interface.go:29-61       -> requester SPI paths (see `spi.py`)

In the dual-pods technique a *server-requesting Pod* is a stub that holds the
TPU allocation in the eyes of the scheduler, while the *server-providing Pod*
actually runs the inference engine but is accounted as using zero TPU chips.
These constants are the single vocabulary binding the two.
"""

# --------------------------------------------------------------------------
# User-facing annotations on the server-requesting Pod.
# --------------------------------------------------------------------------

#: Annotation holding a template that transforms the requesting Pod's
#: (de-individualized) spec into the providing Pod's spec — a strategic-merge
#: patch rendered with :class:`ProviderData`. Mutually exclusive with
#: :data:`INFERENCE_SERVER_CONFIG_ANNOTATION`.
SERVER_PATCH_ANNOTATION = "dual-pods.llm-d.ai/server-patch"

#: Annotation naming the InferenceServerConfig the providing Pod uses
#: (launcher-based path). Mutually exclusive with
#: :data:`SERVER_PATCH_ANNOTATION`.
INFERENCE_SERVER_CONFIG_ANNOTATION = "dual-pods.llm-d.ai/inference-server-config"

#: Annotation maintained by the dual-pods controller reporting
#: :class:`~..api.types.ServerRequestingPodStatus` as JSON.
STATUS_ANNOTATION = "dual-pods.llm-d.ai/status"

#: Name of the container (in the requesting Pod) that the server patch
#: describes and that the providing Pod actually runs.
INFERENCE_SERVER_CONTAINER_NAME = "inference-server"

#: Annotation naming the port of the requester stub's SPI server.
ADMIN_PORT_ANNOTATION = "dual-pods.llm-d.ai/admin-port"

#: Default SPI port of the requester stub.
ADMIN_PORT_DEFAULT = "8081"

# --------------------------------------------------------------------------
# FYI annotations/labels emitted by the dual-pods controller.
# --------------------------------------------------------------------------

#: FYI annotation listing the accelerator (TPU chip) IDs associated with a
#: requesting/providing Pod pair.
ACCELERATORS_ANNOTATION = "dual-pods.llm-d.ai/accelerators"

#: FYI annotation marking a providing Pod as launcher-based.
LAUNCHER_BASED_ANNOTATION = "dual-pods.llm-d.ai/launcher-based"

#: FYI label: while bound, present on both Pods with the other Pod's name.
DUAL_LABEL = "dual-pods.llm-d.ai/dual"

#: FYI label on a bound requesting Pod: the engine instance ID.
INSTANCE_LABEL = "dual-pods.llm-d.ai/instance"

#: FYI label on providing Pods: "true"/"false" — whether (all instances of)
#: the provider are asleep.
SLEEPING_LABEL = "dual-pods.llm-d.ai/sleeping"

# --------------------------------------------------------------------------
# Controller-internal shared keys (dual-pods controller <-> populator <->
# launcher template builder).
# --------------------------------------------------------------------------

#: Annotation on a providing Pod naming the requesting Pod bound to it
#: ("<name>" or "<name>/<uid>"): presence == bound.
REQUESTER_ANNOTATION = "dual-pods.llm-d.ai/requester"

COMPONENT_LABEL = "app.kubernetes.io/component"
LAUNCHER_COMPONENT = "launcher"

#: Label on launcher Pods naming their LauncherConfig.
LAUNCHER_CONFIG_NAME_LABEL = "dual-pods.llm-d.ai/launcher-config-name"

#: Label on launcher Pods naming their Node.
NODE_NAME_LABEL = "dual-pods.llm-d.ai/node-name"

#: Annotation: node-specialized hash of the launcher config a providing Pod
#: was built from.
LAUNCHER_CONFIG_HASH_ANNOTATION = "dual-pods.llm-d.ai/launcher-config-hash"

#: Annotation: node-independent launcher template hash, for drift detection
#: by the populator.
LAUNCHER_TEMPLATE_HASH_ANNOTATION = "dual-pods.llm-d.ai/launcher-populator-template-hash"

#: Port on which every launcher exposes its instance-management REST API.
LAUNCHER_SERVICE_PORT = 8001

#: Annotation: per-pod override of LAUNCHER_SERVICE_PORT. Needed when the
#: LauncherConfig pod template uses hostNetwork (accelerator-host access):
#: two launchers on one node then share the host's port space, and the
#: populator must give the second a distinct port — the reference handles
#: the same same-node port collision by spawning a differently-ported
#: launcher (test/e2e/test-cases.sh:320).
LAUNCHER_PORT_ANNOTATION = "dual-pods.llm-d.ai/launcher-port"

# --------------------------------------------------------------------------
# Instance state persisted on launcher Pods (restart recovery).
# Reference: pkg/controller/dual-pods/controller.go:63-115.
# --------------------------------------------------------------------------

#: Annotation: ID of the engine instance serving the bound requester.
INSTANCE_ID_ANNOTATION = "dual-pods.llm-d.ai/instance-id"

#: Annotation: port the bound instance serves on.
SERVER_PORT_ANNOTATION = "dual-pods.llm-d.ai/server-port"

#: Annotation: JSON of the engine config the bound instance was created with.
ENGINE_CONFIG_ANNOTATION = "dual-pods.llm-d.ai/engine-config"

#: Annotation: JSON of the ISC routing labels/annotations stamped while bound.
ISC_ROUTING_METADATA_ANNOTATION = "dual-pods.llm-d.ai/isc-routing-metadata"

#: Annotation patched by the launcher notifier sidecar: SHA-256 signature of
#: the sorted (instance_id, status) pairs — turns node-local instance state
#: changes into Pod events. Reference: launcher_pod_notifier.py:16-198.
INSTANCE_SIGNATURE_ANNOTATION = "dual-pods.llm-d.ai/vllm-instance-signature"

# --------------------------------------------------------------------------
# TPU-specific additions (no GPU-reference equivalent).
# --------------------------------------------------------------------------

#: Resource name of TPU chips in Kubernetes.
TPU_RESOURCE = "google.com/tpu"

#: Annotation on Nodes / providing Pods recording the slice topology
#: (e.g. "2x4" for a v5e-8 host). The controller's placement logic is
#: topology-aware, not a flat chip-index space.
SLICE_TOPOLOGY_ANNOTATION = "dual-pods.llm-d.ai/tpu-topology"

#: Env var pinning the set of TPU chips visible to an engine process
#: (comma-separated local chip indices) — the TPU analogue of
#: CUDA_VISIBLE_DEVICES.
TPU_VISIBLE_DEVICES_ENV = "TPU_VISIBLE_DEVICES"

#: Env vars used to run multiple engine processes on one TPU host without
#: the device plugin arbitrating chips.
TPU_PROCESS_BOUNDS_ENV = "TPU_PROCESS_BOUNDS"
TPU_CHIPS_PER_PROCESS_BOUNDS_ENV = "TPU_CHIPS_PER_PROCESS_BOUNDS"

#: Name of the ConfigMap mapping node -> chip ID <-> local index/coords
#: (the reference's `gpu-map`, generalized to chips with ICI coordinates).
CHIP_MAP_CONFIGMAP = "chip-map"

# --------------------------------------------------------------------------
# Engine admin API (contract kept engine-agnostic, mirroring vLLM sleep mode;
# reference: pkg/controller/dual-pods/inference-server.go:1497,1712,1984).
# --------------------------------------------------------------------------

ENGINE_SLEEP_PATH = "/sleep"
ENGINE_WAKE_PATH = "/wake_up"
ENGINE_IS_SLEEPING_PATH = "/is_sleeping"
