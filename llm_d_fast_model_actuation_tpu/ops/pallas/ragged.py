"""Ragged paged attention as a Pallas TPU kernel.

Attention for a token-packed mixed batch (PAPERS.md "Ragged Paged
Attention"): one flat ``[tokens]`` buffer whose rows are drawn from many
sequences — prefill segments, suffix continuations, and decode steps
together — each row attending over its OWN sequence's paged KV at
positions <= its own. The XLA twin in ``ops/attention.py`` gathers every
row's full ``ctx = pages_per_seq * page_size`` context (O(tokens * ctx)
HBM traffic regardless of real lengths); this kernel walks only the
``ceil((pos + 1) / page_size)`` pages each row block actually needs,
double-buffering the HBM->VMEM page DMA behind the per-page
flash-attention accumulation — the same discipline as the decode kernel
(ops/pallas/decode.py), generalized from one query row to a block.

Packing contract (the engine's packer upholds it, engine/engine.py):

  * rows belonging to one sequence are CONTIGUOUS in the buffer and
    carry consecutive positions (a segment is one run of tokens);
  * every sequence's run starts on a ``block_rows`` boundary, so each
    kernel block belongs to AT MOST ONE sequence — that alignment is
    what turns "ragged" into a regular grid: block metadata is just
    (page-table row, first position, valid rows), scalar-prefetched;
  * padding rows (``row_slot < 0``) fill alignment gaps and the buffer
    tail; a fully-padded block does no page DMA and writes zeros.

Grid: one program per row block. GQA reads each KV head's page tile once
per block and loops the query heads of its group over it — repeated KV
heads are never materialized, mirroring the decode kernel.

Meshes: the kernel body is a single-device program (it walks the page
pool with raw HBM DMA), and :func:`ragged_paged_attention_pallas_sharded`
ports it to tp meshes by wrapping it in ``shard_map`` over the ``tp``
axis — the axis the engine already shards KV heads and the page pool
over (``PagePool.create`` places pages at ``P(None, None, None, 'tp',
None)``). Each shard walks its OWN head slice of the page pool with the
same replicated block metadata; head-sharded GQA needs no cross-shard
softmax, because every query head's softmax completes inside the shard
that owns its KV-head group. Routing between the two entry points (and
the XLA twin) lives in ``ops/attention.py:resolve_ragged_impl``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _ragged_kernel(
    # scalar prefetch
    meta_ref,  # [num_blocks, 3] SMEM — (row_slot, pos0, nvalid) per block
    page_table_ref,  # [rows, pages_per_seq] SMEM
    # inputs
    q_ref,  # [block_rows, heads, head_dim] VMEM
    k_hbm,  # [num_pages, page_size, kv_heads, head_dim] HBM/ANY
    v_hbm,  # same
    # output
    o_ref,  # [block_rows, heads, head_dim] VMEM
    # scratch
    k_buf,  # [2, page_size, kv_heads, head_dim] VMEM
    v_buf,  # same
    sems,  # DMA sems [2, 2]
    *,
    block_rows: int,
    page_size: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
):
    i = pl.program_id(0)
    group = num_heads // num_kv_heads
    slot = jnp.maximum(meta_ref[i, 0], 0)  # clamped; nvalid=0 masks all
    pos0 = meta_ref[i, 1]
    nvalid = meta_ref[i, 2]
    # pages holding cache entries [0, pos_last + 1): the block's last
    # valid row sits at absolute position pos0 + nvalid - 1, and its own
    # KV was scattered before the kernel ran (scatter-first semantics)
    num_pages = jax.lax.div(pos0 + nvalid + page_size - 1, page_size)

    def page_dma(buf, hbm, buf_slot, p, sem_row):
        return pltpu.make_async_copy(
            hbm.at[page_table_ref[slot, p]],
            buf.at[buf_slot],
            sems.at[sem_row, buf_slot],
        )

    @pl.when(num_pages > 0)
    def _():
        page_dma(k_buf, k_hbm, 0, 0, 0).start()
        page_dma(v_buf, v_hbm, 0, 0, 1).start()

    q = q_ref[...].astype(jnp.float32) * (head_dim**-0.5)  # [B, heads, d]
    row = jax.lax.broadcasted_iota(jnp.int32, (block_rows, 1), 0)
    q_pos = pos0 + row  # [B, 1] absolute position per row
    row_valid = row < nvalid  # [B, 1]

    # Online-softmax state carried per QUERY head (python tuples over the
    # static head axis — in-kernel scatter is not lowerable on TPU,
    # whole-array replacement is). Each KV head's page tile is read once
    # per page and reused by every query head of its group.
    def body(p, carry):
        ms, ls, accs = carry  # tuples of [B, 1], [B, 1], [B, d]
        buf_slot = jax.lax.rem(p, 2)

        @pl.when(p + 1 < num_pages)
        def _():
            nxt = jax.lax.rem(p + 1, 2)
            page_dma(k_buf, k_hbm, nxt, p + 1, 0).start()
            page_dma(v_buf, v_hbm, nxt, p + 1, 1).start()

        page_dma(k_buf, k_hbm, buf_slot, p, 0).wait()
        page_dma(v_buf, v_hbm, buf_slot, p, 1).wait()

        tok0 = p * page_size
        tok_idx = tok0 + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        # causal over the row's own sequence: cache entry <= own position
        mask = (tok_idx <= q_pos) & row_valid  # [B, page_size]

        new_ms = list(ms)
        new_ls = list(ls)
        new_accs = list(accs)
        for g in range(num_kv_heads):
            kg = k_buf[buf_slot, :, g, :].astype(jnp.float32)  # [page, d]
            vg = v_buf[buf_slot, :, g, :].astype(jnp.float32)
            for j in range(group):
                h = g * group + j
                qh = q[:, h, :]  # [B, d]
                logits = jax.lax.dot_general(
                    qh, kg, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [B, page_size]
                logits = jnp.where(mask, logits, NEG_INF)
                m_cur = jnp.maximum(
                    new_ms[h], logits.max(axis=-1, keepdims=True)
                )
                alpha = jnp.exp(new_ms[h] - m_cur)
                probs = jnp.exp(logits - m_cur)
                l_cur = new_ls[h] * alpha + probs.sum(axis=-1, keepdims=True)
                pv = jax.lax.dot_general(
                    probs, vg, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [B, d]
                new_ms[h] = m_cur
                new_ls[h] = l_cur
                new_accs[h] = new_accs[h] * alpha + pv
        return tuple(new_ms), tuple(new_ls), tuple(new_accs)

    m0 = tuple(
        jnp.full((block_rows, 1), NEG_INF, jnp.float32)
        for _ in range(num_heads)
    )
    l0 = tuple(
        jnp.zeros((block_rows, 1), jnp.float32) for _ in range(num_heads)
    )
    acc0 = tuple(
        jnp.zeros((block_rows, head_dim), jnp.float32)
        for _ in range(num_heads)
    )
    ms, ls, accs = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))

    for h in range(num_heads):
        l = ls[h]
        out = jnp.where(l > 0, accs[h] / jnp.where(l > 0, l, 1.0), 0.0)
        o_ref[:, h, :] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def ragged_paged_attention_pallas(
    q: jnp.ndarray,  # [tokens, heads, head_dim] — flat packed buffer
    k_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [rows, pages_per_seq] int32
    row_slot: jnp.ndarray,  # [tokens] int32; -1 = padding row
    positions: jnp.ndarray,  # [tokens] int32 absolute positions
    block_rows: int = 8,
    interpret: bool = False,
) -> jnp.ndarray:
    tokens, num_heads, head_dim = q.shape
    _, page_size, num_kv_heads, _ = k_pages.shape
    if tokens % block_rows != 0:
        raise ValueError(
            f"tokens ({tokens}) must be a multiple of block_rows "
            f"({block_rows}) — the engine pads the packed buffer"
        )
    nb = tokens // block_rows

    # Per-block metadata from the per-row arrays, relying on the packing
    # contract (module docstring): a block's valid rows are a prefix, all
    # of one sequence, position-consecutive — so (first slot, first
    # position, count) describes the whole block.
    rs = row_slot.reshape(nb, block_rows).astype(jnp.int32)
    nvalid = (rs >= 0).sum(axis=1).astype(jnp.int32)
    pos0 = positions.reshape(nb, block_rows)[:, 0].astype(jnp.int32)
    meta = jnp.stack(
        [rs[:, 0], jnp.where(nvalid > 0, pos0, 0), nvalid], axis=1
    )

    kernel = functools.partial(
        _ragged_kernel,
        block_rows=block_rows,
        page_size=page_size,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        head_dim=head_dim,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec(
                (block_rows, num_heads, head_dim),
                lambda i, *_: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (block_rows, num_heads, head_dim),
            lambda i, *_: (i, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, num_kv_heads, head_dim), k_pages.dtype),
            pltpu.VMEM((2, page_size, num_kv_heads, head_dim), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(meta, page_table.astype(jnp.int32), q, k_pages, v_pages)


def ragged_paged_attention_pallas_sharded(
    mesh,
    q: jnp.ndarray,  # [tokens, heads, head_dim]
    k_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [rows, pages_per_seq] int32
    row_slot: jnp.ndarray,  # [tokens] int32; -1 = padding row
    positions: jnp.ndarray,  # [tokens] int32 absolute positions
    block_rows: int = 8,
    interpret: bool = False,
) -> jnp.ndarray:
    """The kernel above on a tp mesh: ``shard_map`` over the ``tp`` axis.

    Query heads, KV heads, and the page pool's kv_heads axis are all
    sharded over ``tp`` (the engine's serving placement), so each shard
    runs the unmodified single-device kernel over its own head slice of
    the pool; the page table and the per-row (slot, position) metadata
    are replicated, and the per-block scalar-prefetch metadata is
    recomputed identically on every shard. No cross-shard collective
    runs inside the attention: with heads grouped to their KV head
    (GQA), every softmax is complete within one shard — the reason a
    head-sharded port needs no distributed online-softmax. Requires
    ``num_kv_heads % tp == 0`` (the same divisibility the NamedSharding
    placement already enforces).

    Composes with jit: the mixed program calls this inside its traced
    body and GSPMD reshards inputs to the declared specs (a no-op for
    activations already sharded over heads). ``interpret=True`` runs the
    per-shard kernel in interpreter mode — how CPU tp-meshes validate
    bit-exactness against the XLA twin (tests/test_ragged.py).
    """
    from jax.sharding import PartitionSpec as P

    from ...utils.compat import shard_map

    kernel = functools.partial(
        ragged_paged_attention_pallas,
        block_rows=block_rows,
        interpret=interpret,
    )
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(
            P(None, "tp", None),  # q: query heads sharded
            P(None, None, "tp", None),  # k_pages: kv heads sharded
            P(None, None, "tp", None),  # v_pages
            P(None, None),  # page_table: replicated
            P(None),  # row_slot: replicated
            P(None),  # positions: replicated
        ),
        out_specs=P(None, "tp", None),
        # the pallas body is opaque to the replication checker; the
        # out_specs above are the contract the caller relies on
        check_rep=False,
    )(q, k_pages, v_pages, page_table, row_slot, positions)
