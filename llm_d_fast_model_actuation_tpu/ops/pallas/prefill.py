"""Blockwise causal (flash) prefill attention as a Pallas TPU kernel.

The XLA reference materializes the full [batch, heads, seq, seq] logits
tensor — O(S^2) HBM traffic and VMEM pressure. This kernel runs the
online-softmax recurrence over a (batch, head, q-block, k-block) grid: only
one [block, head_dim] K tile and V tile are VMEM-resident per step (O(S)
footprint, so long contexts fit), the running max / denominator / output
accumulator live in VMEM scratch that persists across the k-block steps, and
K blocks strictly above the causal diagonal skip their compute entirely.

GQA is handled in the index map: query head h reads KV head h // group, so
repeated KV heads are never materialized.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...utils.compat import tpu_compiler_params

NEG_INF = -1e30


def _prefill_kernel(
    seq_lens_ref,  # [batch] SMEM (scalar prefetch)
    q_ref,  # [1, 1, Bq, d] VMEM
    k_ref,  # [1, 1, Bk, d] VMEM
    v_ref,  # [1, 1, Bk, d] VMEM
    o_ref,  # [1, 1, Bq, d] VMEM (revisited across k blocks)
    m_scr,  # [Bq, 1] f32 VMEM scratch
    l_scr,  # [Bq, 1] f32 VMEM scratch
    acc_scr,  # [Bq, d] f32 VMEM scratch
    *,
    block_q: int,
    block_k: int,
    head_dim: int,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)
    seq_len = seq_lens_ref[b]

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full((block_q, 1), NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros((block_q, 1), jnp.float32)
        acc_scr[:] = jnp.zeros((block_q, head_dim), jnp.float32)

    # causal: this K block contributes only if its first position can be seen
    # by the last query position of the q block
    @pl.when(ki * block_k <= qi * block_q + block_q - 1)
    def _():
        q = q_ref[0, 0].astype(jnp.float32) * (head_dim**-0.5)  # [Bq, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [Bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bq, Bk]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0
        )
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        mask = (k_pos <= q_pos) & (k_pos < seq_len)
        logits = jnp.where(mask, logits, NEG_INF)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, logits.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(logits - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + probs.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            probs, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ki == num_k - 1)
    def _():
        l = l_scr[:]
        out = jnp.where(l > 0, acc_scr[:] / jnp.where(l > 0, l, 1.0), 0.0)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "interpret"))
def causal_prefill_attention_pallas(
    q: jnp.ndarray,  # [batch, seq, heads, head_dim]
    k: jnp.ndarray,  # [batch, seq, kv_heads, head_dim]
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,  # [batch] int32
    block_q: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    batch, seq, num_heads, head_dim = q.shape
    num_kv_heads = k.shape[2]
    group = num_heads // num_kv_heads
    block_q = min(block_q, seq)
    block_k = block_q
    if seq % block_q != 0:
        raise ValueError(f"seq ({seq}) must be a multiple of block_q ({block_q})")

    kernel = functools.partial(
        _prefill_kernel, block_q=block_q, block_k=block_k, head_dim=head_dim
    )
    # head-major layout so the tiled (last two) dims are [seq, head_dim]
    qt = q.transpose(0, 2, 1, 3)  # [b, h, s, d]
    kt = k.transpose(0, 2, 1, 3)  # [b, kvh, s, d]
    vt = v.transpose(0, 2, 1, 3)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(batch, num_heads, seq // block_q, seq // block_k),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, head_dim),
                lambda b, h, i, j, *_: (b, h, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, head_dim),
                lambda b, h, i, j, *_: (b, h // group, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, head_dim),
                lambda b, h, i, j, *_: (b, h // group, j, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, head_dim),
            lambda b, h, i, j, *_: (b, h, i, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        grid_spec=grid_spec,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(seq_lens.astype(jnp.int32), qt, kt, vt)
    return out.transpose(0, 2, 1, 3)
