"""Pallas TPU kernels for the serving hot path.

Same signatures as the pure-JAX reference ops in `ops/attention.py`; the
dispatcher there selects an implementation (`set_attention_impl`). Kernels
run in interpreter mode off-TPU so the whole suite is testable on CPU.
"""

from .decode import (
    paged_decode_attention_inline_pallas,
    paged_decode_attention_pallas,
)
from .prefill import causal_prefill_attention_pallas
from .ragged import (
    ragged_paged_attention_pallas,
    ragged_paged_attention_pallas_sharded,
)

__all__ = [
    "paged_decode_attention_inline_pallas",
    "paged_decode_attention_pallas",
    "causal_prefill_attention_pallas",
    "ragged_paged_attention_pallas",
    "ragged_paged_attention_pallas_sharded",
]
