"""Paged decode attention as a Pallas TPU kernel.

One decode step against the paged KV cache. The XLA reference in
`ops/attention.py` gathers ALL `pages_per_seq` pages for every sequence and
materializes GQA-repeated K/V — O(batch * ctx_max) HBM traffic regardless of
actual sequence lengths. This kernel reads only the pages each sequence
actually occupies (`ceil(seq_len / page_size)` of them), double-buffering the
HBM->VMEM page DMA behind the per-page flash-attention accumulation, and
never materializes repeated KV heads. Decode is HBM-bandwidth-bound, so
bytes-not-read is time-not-spent.

Layout contract (shared with the engine's KV pool):
  k_pages, v_pages: [num_pages, page_size, kv_heads, head_dim]  (HBM)
  page_table:       [batch, pages_per_seq] int32  (scalar-prefetched)
  seq_lens:         [batch] int32, length INCLUDING the new token
  q:                [batch, heads, head_dim]

For best MXU/VPU utilization pick page_size a multiple of 128 on real TPU
(the engine's `page_size` knob); smaller pages still work, padded to lanes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [batch, pages_per_seq] SMEM
    seq_lens_ref,  # [batch] SMEM
    # inputs
    q_ref,  # [1, heads, head_dim] VMEM
    k_hbm,  # [num_pages, page_size, kv_heads, head_dim] HBM/ANY
    v_hbm,  # same
    # output
    o_ref,  # [1, heads, head_dim] VMEM
    # scratch
    k_buf,  # [2, page_size, kv_heads, head_dim] VMEM
    v_buf,  # same
    sems,  # DMA sems [2, 2]
    *,
    page_size: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
):
    b = pl.program_id(0)
    group = num_heads // num_kv_heads
    seq_len = seq_lens_ref[b]
    num_pages = jax.lax.div(seq_len + page_size - 1, page_size)

    def page_dma(buf, hbm, slot, p, sem_row):
        return pltpu.make_async_copy(
            hbm.at[page_table_ref[b, p]],
            buf.at[slot],
            sems.at[sem_row, slot],
        )

    @pl.when(num_pages > 0)
    def _():
        page_dma(k_buf, k_hbm, 0, 0, 0).start()
        page_dma(v_buf, v_hbm, 0, 0, 1).start()

    q = q_ref[0].astype(jnp.float32) * (head_dim**-0.5)  # [heads, head_dim]

    # Online-softmax state is carried per KV head (tuples over the static
    # kv-head axis) — in-kernel scatter is not lowerable on TPU, whole-array
    # replacement is.
    def body(p, carry):
        ms, ls, accs = carry  # tuples of [group,1], [group,1], [group,d]
        slot = jax.lax.rem(p, 2)

        @pl.when(p + 1 < num_pages)
        def _():
            nxt = jax.lax.rem(p + 1, 2)
            page_dma(k_buf, k_hbm, nxt, p + 1, 0).start()
            page_dma(v_buf, v_hbm, nxt, p + 1, 1).start()

        page_dma(k_buf, k_hbm, slot, p, 0).wait()
        page_dma(v_buf, v_hbm, slot, p, 1).wait()

        # tokens beyond seq_len in the (last) page are masked out
        tok0 = p * page_size
        tok_idx = tok0 + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        valid = tok_idx < seq_len  # [1, page_size]

        new_ms, new_ls, new_accs = [], [], []
        for g in range(num_kv_heads):
            qg = q[g * group : (g + 1) * group]  # [group, head_dim]
            kg = k_buf[slot, :, g, :].astype(jnp.float32)  # [page, head_dim]
            vg = v_buf[slot, :, g, :].astype(jnp.float32)
            logits = jax.lax.dot_general(
                qg,
                kg,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [group, page_size]
            logits = jnp.where(valid, logits, NEG_INF)

            m_cur = jnp.maximum(ms[g], logits.max(axis=-1, keepdims=True))
            alpha = jnp.exp(ms[g] - m_cur)
            probs = jnp.exp(logits - m_cur)
            l_cur = ls[g] * alpha + probs.sum(axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                probs,
                vg,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [group, head_dim]
            new_ms.append(m_cur)
            new_ls.append(l_cur)
            new_accs.append(accs[g] * alpha + pv)
        return tuple(new_ms), tuple(new_ls), tuple(new_accs)

    m0 = tuple(jnp.full((group, 1), NEG_INF, jnp.float32) for _ in range(num_kv_heads))
    l0 = tuple(jnp.zeros((group, 1), jnp.float32) for _ in range(num_kv_heads))
    acc0 = tuple(
        jnp.zeros((group, head_dim), jnp.float32) for _ in range(num_kv_heads)
    )
    ms, ls, accs = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))

    l = jnp.concatenate(ls, axis=0)  # [heads, 1]
    acc = jnp.concatenate(accs, axis=0)  # [heads, head_dim]
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    o_ref[0] = out.astype(o_ref.dtype)


def _decode_kernel_inline(
    # scalar prefetch
    page_table_ref,  # [batch, pages_per_seq] SMEM
    pos_ref,  # [batch] SMEM — position of the new token (cache holds < pos)
    # inputs
    q_ref,  # [1, heads, head_dim] VMEM
    knew_ref,  # [1, kv_heads, head_dim] VMEM — the new token's K (not yet in cache)
    vnew_ref,  # [1, kv_heads, head_dim] VMEM
    k_hbm,  # [num_pages, page_size, kv_heads, head_dim] HBM/ANY
    v_hbm,  # same
    # output
    o_ref,  # [1, heads, head_dim] VMEM
    # scratch
    k_buf,  # [2, page_size, kv_heads, head_dim] VMEM
    v_buf,  # same
    sems,  # DMA sems [2, 2]
    *,
    page_size: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
):
    """Decode attention with the new token's K/V passed inline (the engine
    defers cache scatters; see ops/attention.py:paged_decode_attention_inline).
    Identical online-softmax structure to `_decode_kernel`, plus one final
    fold of the inline token into the running (m, l, acc) state."""
    b = pl.program_id(0)
    group = num_heads // num_kv_heads
    pos = pos_ref[b]
    num_pages = jax.lax.div(pos + page_size - 1, page_size)

    def page_dma(buf, hbm, slot, p, sem_row):
        return pltpu.make_async_copy(
            hbm.at[page_table_ref[b, p]],
            buf.at[slot],
            sems.at[sem_row, slot],
        )

    @pl.when(num_pages > 0)
    def _():
        page_dma(k_buf, k_hbm, 0, 0, 0).start()
        page_dma(v_buf, v_hbm, 0, 0, 1).start()

    q = q_ref[0].astype(jnp.float32) * (head_dim**-0.5)  # [heads, head_dim]

    def body(p, carry):
        ms, ls, accs = carry
        slot = jax.lax.rem(p, 2)

        @pl.when(p + 1 < num_pages)
        def _():
            nxt = jax.lax.rem(p + 1, 2)
            page_dma(k_buf, k_hbm, nxt, p + 1, 0).start()
            page_dma(v_buf, v_hbm, nxt, p + 1, 1).start()

        page_dma(k_buf, k_hbm, slot, p, 0).wait()
        page_dma(v_buf, v_hbm, slot, p, 1).wait()

        tok0 = p * page_size
        tok_idx = tok0 + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        valid = tok_idx < pos  # strictly past tokens

        new_ms, new_ls, new_accs = [], [], []
        for g in range(num_kv_heads):
            qg = q[g * group : (g + 1) * group]
            kg = k_buf[slot, :, g, :].astype(jnp.float32)
            vg = v_buf[slot, :, g, :].astype(jnp.float32)
            logits = jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            logits = jnp.where(valid, logits, NEG_INF)
            m_cur = jnp.maximum(ms[g], logits.max(axis=-1, keepdims=True))
            alpha = jnp.exp(ms[g] - m_cur)
            probs = jnp.exp(logits - m_cur)
            l_cur = ls[g] * alpha + probs.sum(axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                probs, vg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            new_ms.append(m_cur)
            new_ls.append(l_cur)
            new_accs.append(accs[g] * alpha + pv)
        return tuple(new_ms), tuple(new_ls), tuple(new_accs)

    m0 = tuple(jnp.full((group, 1), NEG_INF, jnp.float32) for _ in range(num_kv_heads))
    l0 = tuple(jnp.zeros((group, 1), jnp.float32) for _ in range(num_kv_heads))
    acc0 = tuple(
        jnp.zeros((group, head_dim), jnp.float32) for _ in range(num_kv_heads)
    )
    ms, ls, accs = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))

    # Fold the inline token (always valid; guarantees l > 0 even at pos == 0).
    out_rows = []
    for g in range(num_kv_heads):
        qg = q[g * group : (g + 1) * group]
        kn = knew_ref[0, g, :].astype(jnp.float32)  # [head_dim]
        vn = vnew_ref[0, g, :].astype(jnp.float32)
        logit = (qg * kn[None, :]).sum(axis=-1, keepdims=True)  # [group, 1]
        m_cur = jnp.maximum(ms[g], logit)
        alpha = jnp.exp(ms[g] - m_cur)
        p_self = jnp.exp(logit - m_cur)
        l_cur = ls[g] * alpha + p_self
        acc = accs[g] * alpha + p_self * vn[None, :]
        out_rows.append(acc / l_cur)
    out = jnp.concatenate(out_rows, axis=0)  # [heads, head_dim]
    o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_inline_pallas(
    q: jnp.ndarray,  # [batch, heads, head_dim]
    k_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,  # [batch, kv_heads, head_dim]
    v_new: jnp.ndarray,
    page_table: jnp.ndarray,  # [batch, pages_per_seq] int32
    positions: jnp.ndarray,  # [batch] int32
    interpret: bool = False,
) -> jnp.ndarray:
    batch, num_heads, head_dim = q.shape
    _, page_size, num_kv_heads, _ = k_pages.shape

    kernel = functools.partial(
        _decode_kernel_inline,
        page_size=page_size,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        head_dim=head_dim,
    )
    row_spec = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda b, *_: (b,) + (0,) * (len(shape) - 1), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch,),
        in_specs=[
            row_spec((1, num_heads, head_dim)),
            row_spec((1, num_kv_heads, head_dim)),
            row_spec((1, num_kv_heads, head_dim)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=row_spec((1, num_heads, head_dim)),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, num_kv_heads, head_dim), k_pages.dtype),
            pltpu.VMEM((2, page_size, num_kv_heads, head_dim), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        page_table.astype(jnp.int32),
        positions.astype(jnp.int32),
        q,
        k_new,
        v_new,
        k_pages,
        v_pages,
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas(
    q: jnp.ndarray,  # [batch, heads, head_dim]
    k_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [batch, pages_per_seq] int32
    seq_lens: jnp.ndarray,  # [batch] int32
    interpret: bool = False,
) -> jnp.ndarray:
    batch, num_heads, head_dim = q.shape
    _, page_size, num_kv_heads, _ = k_pages.shape

    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        head_dim=head_dim,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch,),
        in_specs=[
            pl.BlockSpec(
                (1, num_heads, head_dim),
                lambda b, *_: (b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, num_heads, head_dim),
            lambda b, *_: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, num_kv_heads, head_dim), k_pages.dtype),
            pltpu.VMEM((2, page_size, num_kv_heads, head_dim), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32), q, k_pages, v_pages)
