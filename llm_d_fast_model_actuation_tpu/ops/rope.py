"""Rotary position embeddings (RoPE), Llama convention.

Tables are precomputed once per (max_len, head_dim, theta) and passed in —
inside `jit` the gather by position fuses into the attention prologue.
"""

import jax.numpy as jnp


def rope_table(max_len: int, head_dim: int, theta: float = 10000.0):
    """(cos, sin) tables of shape [max_len, head_dim//2], fp32."""
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos = jnp.arange(max_len, dtype=jnp.float32)
    angles = pos[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, cos_tab, sin_tab):
    """Rotate q or k by position.

    x: [..., seq, heads, head_dim]; positions: [..., seq] int32.
    Uses the "split halves" (rotate-half) layout, matching HF Llama.
    """
    dtype = x.dtype
    cos = cos_tab[positions].astype(jnp.float32)  # [..., seq, half]
    sin = sin_tab[positions].astype(jnp.float32)
    xf = x.astype(jnp.float32)
    half = xf.shape[-1] // 2
    x1, x2 = xf[..., :half], xf[..., half:]
    # broadcast cos/sin over the heads axis
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(dtype)
