"""Rotary position embeddings (RoPE), Llama convention.

Tables are precomputed once per (max_len, head_dim, theta) and passed in —
inside `jit` the gather by position fuses into the attention prologue.
"""

import math

import jax.numpy as jnp


def _scale_freqs(freqs, scaling):
    """Apply an HF-style rope_scaling spec to the inverse frequencies.

    `scaling` is a hashable tuple (models/llama.py `LlamaConfig.rope_scaling`):
      ("linear", factor)  — divide every frequency by factor
      ("llama3", factor, low_freq_factor, high_freq_factor, original_max)
        — Llama-3.1's banded NTK scheme: low-frequency bands divide by
        factor, high-frequency bands pass through, mid bands interpolate
        (matches transformers' `_compute_llama3_parameters`).
    """
    kind = scaling[0]
    if kind == "linear":
        return freqs / scaling[1]
    if kind == "llama3":
        _, factor, low_ff, high_ff, orig_max = scaling
        low_wavelen = orig_max / low_ff
        high_wavelen = orig_max / high_ff
        wavelen = 2.0 * math.pi / freqs
        scaled = freqs / factor
        smooth = (orig_max / wavelen - low_ff) / (high_ff - low_ff)
        mid = (1.0 - smooth) * scaled + smooth * freqs
        out = jnp.where(wavelen > low_wavelen, scaled, freqs)
        is_mid = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
        return jnp.where(is_mid, mid, out)
    raise ValueError(f"unsupported rope scaling {kind!r}")


def rope_table(
    max_len: int, head_dim: int, theta: float = 10000.0, scaling=None
):
    """(cos, sin) tables of shape [max_len, head_dim//2], fp32."""
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if scaling is not None:
        freqs = _scale_freqs(freqs, tuple(scaling))
    pos = jnp.arange(max_len, dtype=jnp.float32)
    angles = pos[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, cos_tab, sin_tab):
    """Rotate q or k by position.

    x: [..., seq, heads, head_dim]; positions: [..., seq] int32.
    Uses the "split halves" (rotate-half) layout, matching HF Llama.
    """
    dtype = x.dtype
    cos = cos_tab[positions].astype(jnp.float32)  # [..., seq, half]
    sin = sin_tab[positions].astype(jnp.float32)
    xf = x.astype(jnp.float32)
    half = xf.shape[-1] // 2
    x1, x2 = xf[..., :half], xf[..., half:]
    # broadcast cos/sin over the heads axis
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(dtype)
