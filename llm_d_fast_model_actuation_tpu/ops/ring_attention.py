"""Ring attention: causal attention over a sequence-sharded batch.

Long-context prefill/training shards the SEQUENCE axis over the mesh's
``sp`` axis. Plain GSPMD would all-gather K/V (O(S) memory per device,
defeating the sharding); ring attention instead rotates K/V chunks around
the ``sp`` ring with `ppermute` while every device accumulates
online-softmax partial results for its local Q chunk — peak memory O(S/n)
per device and the transfers ride ICI neighbor links (the "How to Scale
Your Model" recipe; same algorithm as Liu et al.'s Ring Attention).

Semantics match `ops.attention.causal_prefill_attention` exactly (causal +
right-padding mask from `seq_lens`, fp32 softmax, GQA without materialized
repeat); a parity test pins it on the virtual CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.compat import shard_map

NEG_INF = -1e30


def _chunk_attend(q, k, v, q_pos, k_pos, seq_lens, m, l, acc):
    """Fold one K/V chunk into the online-softmax state for the local Q.

    q: [b, Cq, h, d]   k/v: [b, Ck, kvh, d]   q_pos: [Cq]  k_pos: [Ck]
    m, l: [b, kvh, g, Cq, 1]   acc: [b, kvh, g, Cq, d]
    """
    b, cq, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    qg = (q.astype(jnp.float32) * (d**-0.5)).astype(q.dtype)
    qg = qg.reshape(b, cq, kvh, g, d)
    logits = jnp.einsum(
        "bqngd,bknd->bngqk", qg, k, preferred_element_type=jnp.float32
    )  # [b, kvh, g, Cq, Ck]
    causal = q_pos[:, None] >= k_pos[None, :]  # [Cq, Ck]
    valid = k_pos[None, :] < seq_lens[:, None]  # [b, Ck]
    mask = causal[None, :, :] & valid[:, None, :]  # [b, Cq, Ck]
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

    m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    probs = jnp.exp(logits - m_new)
    l_new = l * alpha + probs.sum(axis=-1, keepdims=True)
    pv = jnp.einsum(
        "bngqk,bknd->bngqd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    acc_new = acc * alpha + pv
    return m_new, l_new, acc_new


def ring_prefill_attention(
    q: jnp.ndarray,  # [b, s, heads, d], roped, sequence-sharded over `axis`
    k: jnp.ndarray,  # [b, s, kv_heads, d]
    v: jnp.ndarray,  # [b, s, kv_heads, d]
    seq_lens: jnp.ndarray,  # [b] int32 (replicated)
    mesh: Mesh,
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Causal prefill attention with the sequence axis sharded over
    ``axis_name``; K/V rotate around the ring, Q stays put."""
    n = mesh.shape[axis_name]
    if n == 1:
        from .attention import causal_prefill_attention

        return causal_prefill_attention(q, k, v, seq_lens)
    b, s, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    assert s % n == 0, f"seq {s} must divide over {axis_name}={n}"
    chunk = s // n

    def local(q, k, v, seq_lens):
        idx = jax.lax.axis_index(axis_name)
        cq = q.shape[1]
        q_pos = idx * chunk + jnp.arange(cq, dtype=jnp.int32)

        m0 = jnp.full((b, kvh, g, cq, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, kvh, g, cq, 1), jnp.float32)
        acc0 = jnp.zeros((b, kvh, g, cq, d), jnp.float32)

        def step(t, carry):
            kv, m, l, acc = carry
            kc, vc = kv
            src = jax.lax.rem(idx - t + n, n)
            k_pos = src * chunk + jnp.arange(chunk, dtype=jnp.int32)
            m, l, acc = _chunk_attend(q, kc, vc, q_pos, k_pos, seq_lens, m, l, acc)
            # rotate the K/V chunk to the next device (neighbor link on ICI)
            kv = jax.tree.map(
                lambda x: jax.lax.ppermute(
                    x, axis_name, [(i, (i + 1) % n) for i in range(n)]
                ),
                (kc, vc),
            )
            return kv, m, l, acc

        (_, m, l, acc) = jax.lax.fori_loop(0, n, step, ((k, v), m0, l0, acc0))
        out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
        # [b, kvh, g, cq, d] -> [b, cq, h, d]
        out = out.transpose(0, 3, 1, 2, 4).reshape(b, cq, h, d)
        return out.astype(q.dtype)

    seq = P(None, axis_name, None, None)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(seq, seq, seq, P()),
        out_specs=seq,
        check_rep=False,
    )(q, k, v, seq_lens)
