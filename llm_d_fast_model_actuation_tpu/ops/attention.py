"""Attention ops: causal prefill and paged decode.

The serving engine keeps the KV cache *paged*: a global pool of fixed-size
pages per layer, with per-sequence page tables — the vLLM paged-KV idea laid
out for TPU: page_size is a multiple of the VPU lane tile, the kv_heads axis
is sharded over the `tp` mesh axis, and the gather by page table lowers to a
dynamic-slice-friendly pattern XLA handles well (a Pallas ragged kernel can
replace it behind the same signature; see `ops/pallas/`).

Shapes (per layer):
  k_pages, v_pages: [num_pages, page_size, kv_heads, head_dim]
  page_table:       [batch, pages_per_seq] int32 (entries past the sequence
                    end are arbitrary; masked by seq_lens)
  seq_lens:         [batch] int32 — tokens currently in cache per sequence

All softmax math is fp32 regardless of the io dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30

#: Selected implementation: "reference" (pure XLA) or "pallas" (TPU kernels,
#: interpreter mode off-TPU). Read at trace time — switch before (re-)jitting.
_IMPL = "reference"


def set_attention_impl(impl: str) -> None:
    global _IMPL
    if impl not in ("reference", "grouped", "pallas"):
        raise ValueError(f"unknown attention impl {impl!r}")
    _IMPL = impl


def get_attention_impl() -> str:
    return _IMPL


def _pallas_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _repeat_kv(x: jnp.ndarray, n_rep: int, axis: int) -> jnp.ndarray:
    """GQA: repeat kv heads to match query heads."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=axis)


def causal_prefill_attention(
    q: jnp.ndarray,  # [batch, seq, heads, head_dim]
    k: jnp.ndarray,  # [batch, seq, kv_heads, head_dim]
    v: jnp.ndarray,  # [batch, seq, kv_heads, head_dim]
    seq_lens: jnp.ndarray,  # [batch] int32: valid prefix length per row
    impl: "str | None" = None,  # None -> module default
) -> jnp.ndarray:
    """Causal self-attention over a (right-padded) prefill batch."""
    if (impl or _IMPL) == "pallas":
        from .pallas import causal_prefill_attention_pallas

        s = q.shape[1]
        block_q = next((bq for bq in (128, 64, 32, 16, 8) if s % bq == 0), None)
        if block_q is not None:
            return causal_prefill_attention_pallas(
                q, k, v, seq_lens, block_q=block_q, interpret=_pallas_interpret()
            )
    b, s, h, d = q.shape
    kvh = k.shape[2]
    k = _repeat_kv(k, h // kvh, axis=2)
    v = _repeat_kv(v, h // kvh, axis=2)

    qf = q.astype(jnp.float32) * (d**-0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))

    pos = jnp.arange(s)
    causal = pos[None, :, None] >= pos[None, None, :]  # [1, q, k]
    valid = pos[None, None, :] < seq_lens[:, None, None]  # [b, 1, k]
    mask = (causal & valid)[:, None, :, :]  # [b, 1, q, k]
    logits = jnp.where(mask, logits, NEG_INF)

    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_decode_attention_inline(
    q: jnp.ndarray,  # [batch, heads, head_dim] — the new token's queries
    k_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    v_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    k_new: jnp.ndarray,  # [batch, kv_heads, head_dim] — the new token's K
    v_new: jnp.ndarray,  # [batch, kv_heads, head_dim] — the new token's V
    page_table: jnp.ndarray,  # [batch, pages_per_seq] int32
    positions: jnp.ndarray,  # [batch] int32 — position of the new token;
    #                          cache entries < position are attended
    impl: "str | None" = None,
) -> jnp.ndarray:
    """Decode attention where the new token's K/V are passed *inline* instead
    of having been scattered into the cache first.

    This is the serving fast path: per-layer cache scatters are the dominant
    non-matmul cost of a decode step on TPU (each XLA scatter on the pool
    re-materializes it), so the engine defers all layers' KV writes to ONE
    scatter after the layer scan and attention reads cache[< position] plus
    the inline (k_new, v_new) as a virtual final cache entry. Numerically
    identical to scatter-then-attend (same softmax over the same set).

    GQA is handled by *grouping* query heads [b, kvh, group, d] — no
    materialized `repeat` of K/V, matmuls run bf16 on the MXU with fp32
    accumulation.
    """
    if (impl or _IMPL) == "pallas":
        from .pallas import paged_decode_attention_inline_pallas

        return paged_decode_attention_inline_pallas(
            q, k_pages, v_pages, k_new, v_new, page_table, positions,
            interpret=_pallas_interpret(),
        )
    b, h, d = q.shape
    kvh = k_pages.shape[2]
    g = h // kvh
    pages_per_seq = page_table.shape[1]
    page_size = k_pages.shape[1]
    ctx = pages_per_seq * page_size

    k = k_pages[page_table].reshape(b, ctx, kvh, d)
    v = v_pages[page_table].reshape(b, ctx, kvh, d)
    qg = (q.astype(jnp.float32) * (d**-0.5)).astype(q.dtype).reshape(b, kvh, g, d)
    logits = jnp.einsum(
        "bngd,bknd->bngk", qg, k, preferred_element_type=jnp.float32
    )
    valid = jnp.arange(ctx)[None, :] < positions[:, None]  # strictly past
    logits = jnp.where(valid[:, None, None, :], logits, NEG_INF)
    self_logit = jnp.einsum(
        "bngd,bnd->bng", qg, k_new.astype(qg.dtype),
        preferred_element_type=jnp.float32,
    )
    all_logits = jnp.concatenate([logits, self_logit[..., None]], axis=-1)
    probs = jax.nn.softmax(all_logits, axis=-1)
    out = jnp.einsum(
        "bngk,bknd->bngd", probs[..., :ctx].astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    out = out + probs[..., ctx:] * v_new.reshape(b, kvh, 1, d).astype(jnp.float32)
    return out.reshape(b, h, d).astype(q.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [batch, heads, head_dim] — one new token per sequence
    k_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    v_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    page_table: jnp.ndarray,  # [batch, pages_per_seq] int32
    seq_lens: jnp.ndarray,  # [batch] int32 (length INCLUDING the new token)
    impl: "str | None" = None,  # None -> module default
) -> jnp.ndarray:
    """One decode step of attention against the paged cache.

    Reference implementation: gather each sequence's pages, flatten to a
    [batch, ctx, kv_heads, head_dim] view, mask past seq_len. ctx =
    pages_per_seq * page_size is static, so the whole step is one fused
    region under jit — no dynamic shapes.
    """
    if (impl or _IMPL) == "pallas":
        from .pallas import paged_decode_attention_pallas

        return paged_decode_attention_pallas(
            q, k_pages, v_pages, page_table, seq_lens, interpret=_pallas_interpret()
        )
    b, h, d = q.shape
    pages_per_seq = page_table.shape[1]
    page_size = k_pages.shape[1]
    kvh = k_pages.shape[2]
    ctx = pages_per_seq * page_size

    def flatten(pages):
        g = pages[page_table]  # [b, pages_per_seq, page_size, kvh, d]
        return g.reshape(b, ctx, kvh, d)

    k = _repeat_kv(flatten(k_pages), h // kvh, axis=2)  # [b, ctx, h, d]
    v = _repeat_kv(flatten(v_pages), h // kvh, axis=2)

    qf = q.astype(jnp.float32) * (d**-0.5)
    logits = jnp.einsum("bhd,bkhd->bhk", qf, k.astype(jnp.float32))
    valid = jnp.arange(ctx)[None, :] < seq_lens[:, None]  # [b, ctx]
    logits = jnp.where(valid[:, None, :], logits, NEG_INF)

    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhk,bkhd->bhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


#: Row-block size of the ragged packing layout (ops/pallas/ragged.py): the
#: engine's packer aligns every sequence's contiguous run of rows in the
#: flat [token_budget] buffer to this boundary, so each kernel block
#: belongs to at most one sequence. Waste per packed segment is < this
#: many rows — against up to 2x for the power-of-two prefill buckets.
RAGGED_BLOCK = 8


def resolve_ragged_impl(impl: str, mesh) -> str:
    """The implementation the RAGGED op runs under for an engine on
    `mesh` (None = single device) — the ONE routing decision for the
    packed data plane, a matrix of device kind x mesh x impl flag:

    ==========  ====================  =================================
    impl flag   mesh=None             single-process tp mesh
    ==========  ====================  =================================
    pallas      Pallas kernel         Pallas kernel under ``shard_map``
                (interpret on CPU)    over the ``tp`` axis
                                      (ops/pallas/ragged.py:
                                      ragged_paged_attention_pallas_
                                      sharded) on TPU meshes — and in
                                      interpreter mode on CPU meshes
                                      whose jaxlib can lower it
                                      (``pallas_interpret_supported``);
                                      the XLA twin otherwise
    grouped /   XLA twin              XLA twin — its gather/scatter
    reference                         GSPMD-partitions: ``k_pages[pt]``
                                      gathers on the replicated page
                                      axis of a pool sharded over
                                      kv_heads, so each device reads
                                      only its own head shard, and the
                                      einsums contract the head-sharded
                                      axes in place
    ==========  ====================  =================================

    KV heads and the page pool are sharded over ``tp`` already
    (``PagePool.create``), so the shard_map port gives each shard the
    same scalar-prefetched block metadata over its own head slice of
    the pool — no cross-shard softmax for head-sharded GQA. The
    engine's bucketed programs keep their configured impl — only the
    packed path routes here. Engines resolved to a non-pallas impl pack
    densely (the twin computes every row independently, so RAGGED_BLOCK
    alignment buys nothing); pallas engines keep the block alignment on
    meshes too."""
    if impl != "pallas" or mesh is None:
        return impl
    if jax.default_backend() == "tpu":
        return "pallas"
    from ..utils.compat import pallas_interpret_supported

    return "pallas" if pallas_interpret_supported() else "grouped"


def ragged_paged_attention(
    q: jnp.ndarray,  # [tokens, heads, head_dim] — flat packed token buffer
    k_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    v_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    page_table: jnp.ndarray,  # [rows, pages_per_seq] int32
    row_slot: jnp.ndarray,  # [tokens] int32 — page_table row per token;
    #                         -1 marks a padding row (output is garbage)
    positions: jnp.ndarray,  # [tokens] int32 — absolute position per token
    impl: "str | None" = None,  # None -> module default
    mesh=None,  # tp mesh for the pallas impl's shard_map port; the XLA
    #            twin never needs it (GSPMD partitions it in place)
) -> jnp.ndarray:
    """Attention for a token-packed mixed batch over the paged cache.

    One flat ``[tokens]`` buffer holds rows drawn from MANY sequences —
    prefill segments, suffix continuations, and decode steps together
    (the mixed-batch serving path, engine/engine.py). Each token carries
    its own (sequence slot, absolute position); its KV has already been
    scattered into the pages (scatter-first, like ``prefill_continue``),
    and it attends over every cache entry of its OWN sequence at
    positions <= its own — which is simultaneously the causal prefill
    mask, the suffix-continuation mask, and the decode mask (the token
    itself is the newest cache entry).

    This XLA twin is the CPU-runnable parity baseline: a gather of each
    token's pages (a dynamic-slice-friendly pattern XLA fuses, exactly
    like ``paged_suffix_attention``) that materializes [tokens, ctx] —
    fine for tests and CPU serving, O(tokens * ctx) HBM traffic on TPU.
    The Pallas kernel behind the same signature (ops/pallas/ragged.py)
    reads only the pages each row block actually needs; it additionally
    requires the packing contract that rows of one sequence are
    contiguous, position-consecutive, and aligned to ``RAGGED_BLOCK``.

    Padding rows (``row_slot < 0``) write nothing (the model's scatter
    drops them) and read row 0's pages fully masked — their output is
    finite garbage the caller ignores.
    """
    if (impl or _IMPL) == "pallas":
        if q.shape[0] % RAGGED_BLOCK == 0:
            if mesh is not None:
                from .pallas import ragged_paged_attention_pallas_sharded

                return ragged_paged_attention_pallas_sharded(
                    mesh, q, k_pages, v_pages, page_table, row_slot,
                    positions, block_rows=RAGGED_BLOCK,
                    interpret=_pallas_interpret(),
                )
            from .pallas import ragged_paged_attention_pallas

            return ragged_paged_attention_pallas(
                q, k_pages, v_pages, page_table, row_slot, positions,
                block_rows=RAGGED_BLOCK, interpret=_pallas_interpret(),
            )
    t, h, d = q.shape
    kvh = k_pages.shape[2]
    g = h // kvh
    pages_per_seq = page_table.shape[1]
    page_size = k_pages.shape[1]
    ctx = pages_per_seq * page_size

    safe = jnp.clip(row_slot, 0, page_table.shape[0] - 1)
    pt = page_table[safe]  # [t, pages_per_seq]
    k = k_pages[pt].reshape(t, ctx, kvh, d)
    v = v_pages[pt].reshape(t, ctx, kvh, d)
    qg = (q.astype(jnp.float32) * (d**-0.5)).astype(q.dtype).reshape(
        t, kvh, g, d
    )
    logits = jnp.einsum(
        "tngd,tknd->tngk", qg, k, preferred_element_type=jnp.float32
    )
    mask = jnp.arange(ctx)[None, :] <= positions[:, None]  # [t, ctx]
    mask = mask & (row_slot >= 0)[:, None]
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "tngk,tknd->tngd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(t, h, d).astype(q.dtype)


def paged_suffix_attention(
    q: jnp.ndarray,  # [batch, s, heads, head_dim] — suffix queries
    k_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    v_pages: jnp.ndarray,  # [num_pages, page_size, kv_heads, head_dim]
    page_table: jnp.ndarray,  # [batch, pages_per_seq] int32
    start: jnp.ndarray,  # [batch] int32 — absolute position of query 0
) -> jnp.ndarray:
    """Causal attention for a prompt SUFFIX over the paged cache.

    The prefix-caching continue path (engine/prefix_cache.py): the cached
    prefix's KV already lives in shared pages, the suffix's KV has just
    been scattered in, and query i at absolute position start+i attends
    to every cache slot <= its own position. Padding queries (past the
    real suffix) produce garbage the caller ignores — same convention as
    the right-padded full prefill. GQA by head grouping (no repeated
    K/V), XLA gather over the table — ctx is static so the whole thing is
    one fused region.
    """
    b, s, h, d = q.shape
    kvh = k_pages.shape[2]
    g = h // kvh
    pages_per_seq = page_table.shape[1]
    page_size = k_pages.shape[1]
    ctx = pages_per_seq * page_size

    k = k_pages[page_table].reshape(b, ctx, kvh, d)
    v = v_pages[page_table].reshape(b, ctx, kvh, d)
    qg = (q.astype(jnp.float32) * (d**-0.5)).astype(q.dtype).reshape(
        b, s, kvh, g, d
    )
    logits = jnp.einsum(
        "bsngd,bknd->bsngk", qg, k, preferred_element_type=jnp.float32
    )
    qpos = start[:, None] + jnp.arange(s)[None, :]  # [b, s] absolute
    mask = jnp.arange(ctx)[None, None, :] <= qpos[:, :, None]  # [b, s, ctx]
    logits = jnp.where(mask[:, :, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bsngk,bknd->bsngd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, s, h, d).astype(q.dtype)
