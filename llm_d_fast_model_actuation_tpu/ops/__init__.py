"""TPU compute ops: reference JAX implementations + Pallas kernels.

Every op has a pure-JAX reference implementation (runs anywhere, used for
CPU-mesh tests and as the numerical oracle) and, where it is on the serving
hot path, a Pallas TPU kernel behind the same signature. Kernel selection is
automatic by backend with an env override (FMA_TPU_FORCE_REFERENCE_OPS=1).
"""

from .norm import rms_norm  # noqa: F401
from .rope import apply_rope, rope_table  # noqa: F401
from .attention import (  # noqa: F401
    causal_prefill_attention,
    paged_decode_attention,
)
