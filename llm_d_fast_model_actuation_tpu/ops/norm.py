"""RMSNorm. Computed in fp32, cast back to the input dtype — bf16 variance
accumulation loses too much precision at hidden sizes >= 4k."""

import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    offset: float = 0.0,
) -> jnp.ndarray:
    """`offset=1.0` gives the Gemma-family convention: weights are stored
    zero-centered and applied as (1 + w)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * (weight.astype(jnp.float32) + offset)).astype(dtype)
