# Build/test entry points (reference: Makefile:21-140).

PYTHON ?= python
IMAGE_REGISTRY ?= ghcr.io/example
IMAGE_TAG ?= latest

.PHONY: test test-fast native bench lint images dryrun clean

# --durations mirrors the CI sweep: the tier-1 run is timeout-bound in
# some containers (ROADMAP), so the slowest tests must be visible
test:
	$(PYTHON) -m pytest tests/ -q --durations=15

test-fast:
	$(PYTHON) -m pytest tests/ -q -x

native:
	$(MAKE) -C native

bench:
	timeout 590 $(PYTHON) bench.py

# simulated actuation benchmark (no cluster, no TPU)
bench-actuation:
	$(PYTHON) -m llm_d_fast_model_actuation_tpu.benchmark --scenario all

dryrun:
	$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

images:
	docker build -f deploy/dockerfiles/Dockerfile.launcher -t $(IMAGE_REGISTRY)/fma-tpu-launcher:$(IMAGE_TAG) .
	docker build -f deploy/dockerfiles/Dockerfile.requester -t $(IMAGE_REGISTRY)/fma-tpu-requester:$(IMAGE_TAG) .
	docker build -f deploy/dockerfiles/Dockerfile.controller -t $(IMAGE_REGISTRY)/fma-tpu-controller:$(IMAGE_TAG) .

clean:
	rm -rf native/build .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
